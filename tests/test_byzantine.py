"""Byzantine adversaries against live consensus networks (ISSUE 9
acceptance): the equivocation -> evidence -> commit pipeline, proposer
equivocation safety, garbage-signature floods vs. the breaker, and the
lying fast-sync peer.
"""

from __future__ import annotations

import time

import pytest

from tendermint_tpu.services.resilient import ResilientVerifier
from tendermint_tpu.services.verifier import HostBatchVerifier
from tendermint_tpu.telemetry import REGISTRY
from tendermint_tpu.telemetry.flightrec import FLIGHT
from tendermint_tpu.testing import (
    ConflictingProposer,
    Equivocator,
    GarbageSigFlooder,
    LyingFastSyncPeer,
    Nemesis,
)
from tendermint_tpu.testing.byzantine import committed_evidence, wait_evidence_committed
from tendermint_tpu.utils.circuit import CircuitBreaker


def _resilient_factory(threshold=2, reset_s=0.5):
    def factory(_i):
        return ResilientVerifier(
            HostBatchVerifier(),
            breaker=CircuitBreaker(
                failure_threshold=threshold, reset_timeout_s=reset_s
            ),
            max_retries=0,
        )

    return factory


class TestEquivocation:
    def test_equivocator_evidence_committed_within_five_heights(self, tmp_path):
        """THE acceptance scenario: a 4-validator net with one
        equivocating validator — honest nodes detect the conflicting
        votes, pool DuplicateVoteEvidence (verified through the batched
        verify seam), gossip it on channel 0x38, and commit it in a
        block within <= 5 heights of the offense; no fork, continuous
        progress, and the flight recorder holds the detection event."""
        with Nemesis(4, home=str(tmp_path)) as net:
            net.wait_height(2, timeout=60)
            eq = Equivocator(net, 3).start()
            try:
                honest = [0, 1, 2]
                found = wait_evidence_committed(
                    net, eq.address, nodes=honest, within_heights=5, timeout=60
                )
                assert eq.equivocations > 0
                # every honest node committed the SAME offender's proof
                for node_idx, height in found.items():
                    evs = [
                        e
                        for h, e in committed_evidence(net, node_idx)
                        if h == height
                    ]
                    assert any(e.address == eq.address for e in evs)
                # liveness continues past the punishment
                net.wait_progress(delta=2, timeout=60)
                net.check_invariants()  # no fork
                # the black box recorded both ends of the pipeline
                assert FLIGHT.recent(kind="evidence_detected")
                assert FLIGHT.recent(kind="evidence_added")
            finally:
                eq.stop()

    def test_equivocation_survives_offender_crash(self, tmp_path):
        """Evidence already pooled must survive the network losing the
        offender: pools are WAL-backed and gossip re-offers pending
        proofs, so commitment happens even after the byzantine node
        goes dark."""
        with Nemesis(4, home=str(tmp_path)) as net:
            net.wait_height(2, timeout=60)
            eq = Equivocator(net, 3).start()
            try:
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline and not any(
                    net.nodes[i].evidence_pool.depth()
                    or committed_evidence(net, i)
                    for i in (0, 1, 2)
                ):
                    time.sleep(0.05)
            finally:
                eq.stop()
            net.crash(3)  # the offender vanishes; 3 honest nodes remain
            wait_evidence_committed(
                net, eq.address, nodes=[0, 1, 2], timeout=60
            )
            net.wait_progress(delta=1, nodes=[0, 1, 2], timeout=60)
            net.check_invariants()


class TestConflictTipsQuorum:
    def test_conflicting_vote_that_tips_quorum_still_commits(self):
        """Deterministic regression for the liveness wedge the full-net
        equivocation runs exposed: a conflicting vote for a
        peer-maj23-tracked block is TALLIED first and raises second
        (`VoteSet._add_verified_vote`), so the +2/3 it just tipped must
        still drive the commit transitions — the evidence handler cannot
        simply swallow the exception, or the height wedges forever (no
        later vote re-triggers; duplicates don't re-add)."""
        import time as _time

        from tendermint_tpu.types.block_id import BlockID
        from tendermint_tpu.types.part_set import PartSetHeader
        from tendermint_tpu.types.vote import (
            VOTE_TYPE_PRECOMMIT,
            VOTE_TYPE_PREVOTE,
            Vote,
        )
        from tests.test_consensus import CHAIN, Fixture

        fx = Fixture(n_vals=4)
        fx.cs.start()
        try:
            bid = fx.proposal_block_id()
            # polka: everyone prevotes the block; our node precommits it
            fx.inject_votes(VOTE_TYPE_PREVOTE, bid, [1, 2, 3])
            fx.wait_step("Precommit")
            pc = fx.cs.votes.precommits(0)

            # validator 3 equivocates: its FAKE precommit lands first,
            # occupying its slot in the canonical vote list
            fake_bid = BlockID(b"\xbe\xef" * 16, PartSetHeader.zero())
            fake = Vote(
                validator_address=fx.privs[3].address,
                validator_index=3,
                height=fx.cs.height,
                round=0,
                timestamp=_time.time_ns(),
                type=VOTE_TYPE_PRECOMMIT,
                block_id=fake_bid,
            )
            fake = fake.with_signature(
                fx.privs[3]._signer.sign(fake.sign_bytes(CHAIN))
            )
            fx.cs.add_vote(fake, peer_id="peer3")
            deadline = _time.time() + 10
            while _time.time() < deadline and pc.get_by_index(3) is None:
                _time.sleep(0.01)
            assert pc.get_by_index(3) is not None

            # a peer claims +2/3 for the REAL block: conflicts against it
            # now tally before raising (reference SetPeerMaj23 semantics)
            pc.set_peer_maj23("claimer", bid)
            # ours + validator 1 = 20/40: below quorum...
            fx.inject_votes(VOTE_TYPE_PRECOMMIT, bid, [1])
            # ...validator 3's REAL precommit tips it to 30/40 >= +2/3
            # AND raises ErrVoteConflictingVotes in the same call
            fx.inject_votes(VOTE_TYPE_PRECOMMIT, bid, [3])
            # pre-fix: permanent wedge here (MockTicker fires no
            # round-skip rescue); post-fix: the height commits
            fx.wait_height(1, timeout=15)
            # the equivocation was still recorded on its way through
            assert FLIGHT.recent(kind="evidence_detected")
        finally:
            fx.cs.stop()


class TestConflictingProposer:
    def test_split_proposal_keeps_safety_and_liveness(self, tmp_path):
        with Nemesis(4, home=str(tmp_path)) as net:
            net.wait_height(2, timeout=60)
            cp = ConflictingProposer(net, 1).start()
            try:
                deadline = time.monotonic() + 45
                while time.monotonic() < deadline and cp.conflicts < 2:
                    time.sleep(0.05)
                assert cp.conflicts >= 1, "proposer never got a turn"
                net.wait_progress(delta=3, timeout=60)
                net.check_invariants()
            finally:
                cp.stop()


class TestGarbageSigFlood:
    def test_flooder_banned_breaker_stays_closed(self, tmp_path):
        """Satellite + acceptance: a sustained forged-sig flood through
        the vote drain AND mempool ingress debits the peer into a ban
        while `tendermint_breaker_state{kind=verify}` stays 0 — False
        verdicts are ADVERSARIAL INPUT, never device failures, so one
        attacker cannot DoS the TPU fast path into host crypto."""
        trips_before = REGISTRY.counter_value(
            "tendermint_breaker_transitions_total", kind="verify", to="open"
        )
        with Nemesis(
            4, home=str(tmp_path), verifier_factory=_resilient_factory()
        ) as net:
            net.wait_height(2, timeout=60)
            flooder = GarbageSigFlooder(net.nodes[0], net.chain_id)
            try:
                # sustained: keep refilling the channel queue until banned
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline and not flooder.banned():
                    flooder.flood_votes(64)
                    flooder.flood_txs(64)
                    time.sleep(0.05)
                assert flooder.banned(), "flooder never banned"
                assert not flooder.reconnect(), "banned peer re-admitted"
                # bad_sig offenses were scored...
                assert (
                    REGISTRY.counter_value(
                        "tendermint_p2p_peer_misbehavior_total", kind="bad_sig"
                    )
                    > 0
                )
                assert (
                    REGISTRY.counter_value("tendermint_p2p_peer_bans_total") > 0
                )
                # ...and the breaker NEVER conflated them with device
                # faults: no trips, every node still closed (= 0)
                assert (
                    REGISTRY.counter_value(
                        "tendermint_breaker_transitions_total",
                        kind="verify",
                        to="open",
                    )
                    == trips_before
                )
                assert all(
                    n.cs.verifier.breaker.state == "closed" for n in net.nodes
                )
                assert (
                    REGISTRY.counter_value("tendermint_breaker_state", kind="verify")
                    == 0
                )
                # honest consensus traffic was never starved
                net.wait_progress(delta=2, timeout=60)
                net.check_invariants()
            finally:
                flooder.stop()


class TestIngressFloodRecovery:
    def test_admission_throughput_recovers_after_flood(self):
        """Acceptance: honest-tx admission throughput after a forged-sig
        flood recovers to within 2x of the pre-flood rate, and the
        verify breaker never opens (the flood degrades nothing)."""
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.abci.client import local_client_creator
        from tendermint_tpu.crypto.keys import gen_priv_key
        from tendermint_tpu.mempool.ingress import SIGNED_TX_MAGIC, make_signed_tx
        from tendermint_tpu.mempool.mempool import Mempool

        verifier = ResilientVerifier(
            HostBatchVerifier(),
            breaker=CircuitBreaker(failure_threshold=2, reset_timeout_s=0.5),
            max_retries=0,
        )
        conns = local_client_creator(KVStoreApp())()
        pool = Mempool(conns.mempool, verifier=verifier, ingress_batch=True)
        priv = gen_priv_key(b"\x11" * 32)
        try:
            def admit_rate(n, tag):
                t0 = time.perf_counter()
                last = None
                for i in range(n):
                    last = pool.check_tx_async(
                        make_signed_tx(priv, b"%s-%d=%d" % (tag, i, i))
                    )
                last.wait(30)
                return n / (time.perf_counter() - t0)

            before = admit_rate(300, b"pre")
            # the flood: forged envelopes, every signature invalid
            bad_sig = REGISTRY.counter_value(
                "tendermint_mempool_txs_total", result="bad_sig"
            )
            last = None
            for i in range(2000):
                forged = (
                    SIGNED_TX_MAGIC
                    + bytes(32)
                    + bytes(64)
                    + b"flood-%d" % i
                )
                last = pool.check_tx_async(forged)
            res = last.wait(30)
            assert not res.is_ok
            assert (
                REGISTRY.counter_value(
                    "tendermint_mempool_txs_total", result="bad_sig"
                )
                - bad_sig
                >= 2000
            )
            # adversarial False verdicts are not device failures
            assert verifier.breaker.state == "closed"
            after = admit_rate(300, b"post")
            assert after >= before / 2, (
                f"admission throughput did not recover: "
                f"{before:.0f} -> {after:.0f} tx/s"
            )
        finally:
            pool.close()


class TestLyingFastSyncPeer:
    def test_forged_chain_rejected_and_liar_banned(self):
        """A fast-syncing node offered a forged chain must apply NONE of
        it, ban the liar (forged_block debit), and finish syncing from
        the honest peer."""
        from tendermint_tpu.abci.apps import KVStoreApp
        from tendermint_tpu.abci.client import local_client_creator
        from tendermint_tpu.blockchain import BlockchainReactor, BlockStore
        from tendermint_tpu.db.kv import MemDB
        from tendermint_tpu.p2p import NodeInfo, Switch, connect_switches
        from tendermint_tpu.state import make_genesis_state

        from tests.helpers import CHAIN_ID, ChainSim

        sim = ChainSim(n_vals=4)
        store = BlockStore(MemDB())
        for _ in range(40):
            block = sim.advance()
            store.save_block(block, block.make_part_set(), sim.commits[-1])

        server = Switch(NodeInfo(node_id="server", moniker="s", chain_id=CHAIN_ID))
        server.add_reactor(
            "blockchain",
            BlockchainReactor(
                state=sim.state,
                store=store,
                app_conn=sim.conns.consensus,
                fast_sync=False,
            ),
        )
        server.start()

        fresh_state = make_genesis_state(MemDB(), sim.genesis)
        fresh_state.save()
        fresh_store = BlockStore(MemDB())
        conns = local_client_creator(KVStoreApp())()
        client_reactor = BlockchainReactor(
            state=fresh_state,
            store=fresh_store,
            app_conn=conns.consensus,
            fast_sync=True,
        )
        client = Switch(NodeInfo(node_id="fresh", moniker="f", chain_id=CHAIN_ID))
        client.add_reactor("blockchain", client_reactor)
        client.start()
        liar = LyingFastSyncPeer(client, CHAIN_ID, claim_height=500)
        try:
            connect_switches(server, client)
            deadline = time.time() + 90
            while time.time() < deadline and fresh_store.height < 39:
                time.sleep(0.05)
            assert fresh_store.height >= 39, "victim never synced honest chain"
            # forged blocks never entered the store
            for h in (1, 20, 39):
                assert (
                    fresh_store.load_block(h).hash() == store.load_block(h).hash()
                )
            assert liar.blocks_served > 0, "liar was never even asked"
            deadline = time.time() + 30
            while time.time() < deadline and not liar.banned():
                time.sleep(0.05)
            assert liar.banned(), "lying peer was not banned"
        finally:
            liar.stop()
            server.stop()
            client.stop()
