"""Telemetry end-to-end against a running node: `GET /metrics` scrape
contents (the ISSUE's acceptance surface) and the `dump_telemetry` RPC.
"""

from __future__ import annotations

import json
import urllib.request

import pytest

from tendermint_tpu.cmd import main as cli_main
from tendermint_tpu.config import Config
from tendermint_tpu.node import Node
from tendermint_tpu.services.resilient import ResilientVerifier
from tendermint_tpu.services.verifier import HostBatchVerifier

pytestmark = pytest.mark.slow


@pytest.fixture()
def solo_node(tmp_path):
    home = str(tmp_path / "solo")
    cli_main(["init", "--home", home, "--chain-id", "telemetry-test"])
    cfg = Config.test_config(home)
    cfg.base.fast_sync = False
    # resilient wrapper on host so breaker series exist on CPU CI
    node = Node(cfg, verifier=ResilientVerifier(HostBatchVerifier()))
    node.start()
    yield node
    node.stop()


def _rpc(port, method, **params):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        out = json.load(resp)
    if "error" in out:
        raise RuntimeError(out["error"])
    return out["result"]


def _parse_samples(text: str) -> dict:
    """Prometheus text -> {sample_line_name{labels}: float}."""
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        key, _, val = line.rpartition(" ")
        try:
            out[key] = float(val)
        except ValueError:
            pass
    return out


class TestMetricsScrape:
    def test_curl_metrics_is_valid_and_populated(self, solo_node):
        # commit a tx so consensus/mempool/WAL series all move
        res = _rpc(solo_node.rpc_port, "broadcast_tx_commit", tx=b"mk=mv".hex())
        assert res["deliver_tx"]["code"] == 0
        solo_node.wait_height(2)

        with urllib.request.urlopen(
            f"http://127.0.0.1:{solo_node.rpc_port}/metrics", timeout=30
        ) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4"
            )
            text = resp.read().decode()
        samples = _parse_samples(text)

        # consensus height + round-phase latency histograms
        assert samples["tendermint_consensus_height"] >= 2
        assert samples['tendermint_consensus_phase_seconds_count{phase="propose"}'] >= 1
        assert samples['tendermint_consensus_phase_seconds_count{phase="commit"}'] >= 1
        assert samples["tendermint_consensus_height_seconds_count"] >= 1
        assert samples["tendermint_consensus_commits_total"] >= 1
        assert samples["tendermint_consensus_txs_committed_total"] >= 1

        # verify/hash batch histograms (host backend on CPU CI)
        assert samples['tendermint_verify_batch_size_count{backend="host"}'] >= 1
        assert samples['tendermint_hash_seconds_count{backend="host"}'] >= 1

        # breaker state series for the resilient verifier
        assert samples['tendermint_breaker_state{kind="verify"}'] == 0  # closed

        # p2p byte rates + mempool depth are exposed (solo node: zeros)
        for name in (
            "tendermint_p2p_sent_bytes_total",
            "tendermint_p2p_recv_bytes_total",
            "tendermint_p2p_peers",
            "tendermint_p2p_send_rate_bytes",
            "tendermint_mempool_size",
        ):
            assert name in samples, name

        # WAL fsync latency moved with the committed inputs
        assert samples["tendermint_wal_fsync_seconds_count"] >= 1
        assert samples["tendermint_mempool_txs_total{result=\"ok\"}"] >= 1

    def test_dump_telemetry_rpc(self, solo_node):
        solo_node.wait_height(1)
        out = _rpc(solo_node.rpc_port, "dump_telemetry", spans=64)
        # the three documented sections
        assert set(out) == {"metrics", "spans", "breakers"}
        m = out["metrics"]
        assert m["tendermint_consensus_height"]["type"] == "gauge"
        assert m["tendermint_consensus_height"]["series"][0]["value"] >= 1
        # consensus phase spans attributed with height/round
        names = {s["name"] for s in out["spans"]}
        assert any(n.startswith("consensus.") for n in names), names
        span = next(s for s in out["spans"] if s["name"] == "consensus.height")
        assert span["attrs"]["height"] >= 1
        assert span["end"] >= span["start"]
        # breaker snapshot rides along for the resilient verifier
        assert out["breakers"]["verifier"]["state"] == "closed"
        assert out["breakers"]["verifier"]["kind"] == "verify"

    def test_dump_telemetry_span_prefix_filter(self, solo_node):
        solo_node.wait_height(1)
        out = _rpc(
            solo_node.rpc_port, "dump_telemetry", spans=32, prefix="consensus."
        )
        assert out["spans"], "expected consensus spans after a commit"
        assert all(s["name"].startswith("consensus.") for s in out["spans"])
