"""BatchVerifier / TreeHasher service layer + mesh-sharded verification."""

import numpy as np
import pytest

from tendermint_tpu.crypto.keys import gen_priv_key
from tendermint_tpu.merkle.simple import (
    simple_hash_from_byte_slices,
    simple_hash_from_hashes,
)
from tendermint_tpu.services import (
    DeviceBatchVerifier,
    HostBatchVerifier,
    TreeHasher,
)


def _triples(n, corrupt=()):
    privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
    msgs = [b"msg-%d" % i for i in range(n)]
    out = []
    for i, (p, m) in enumerate(zip(privs, msgs)):
        sig = p.sign(m)
        if i in corrupt:
            sig = sig[:8] + bytes([sig[8] ^ 1]) + sig[9:]
        out.append((p.pub_key.data, m, sig))
    return out


class TestBatchVerifier:
    @pytest.mark.parametrize("cls", [HostBatchVerifier, DeviceBatchVerifier])
    def test_verify_batch_localizes_failures(self, cls):
        # min_device_batch=1 keeps DeviceBatchVerifier on the kernel path
        # (the default threshold would silently route to the host)
        v = cls() if cls is HostBatchVerifier else cls(min_device_batch=1)
        verdict = v.verify_batch(_triples(6, corrupt={1, 4}))
        assert verdict.tolist() == [True, False, True, True, False, True]

    def test_accumulate_flush(self):
        v = DeviceBatchVerifier(min_device_batch=1)
        triples = _triples(5, corrupt={2})
        idxs = [v.add(*t) for t in triples]
        assert idxs == [0, 1, 2, 3, 4]
        assert v.pending() == 5
        verdict = v.flush()
        assert verdict.tolist() == [True, True, False, True, True]
        assert v.pending() == 0
        assert v.flush().shape == (0,)

    def test_verify_one(self):
        v = HostBatchVerifier()
        (pk, m, sig) = _triples(1)[0]
        assert v.verify_one(pk, m, sig)
        assert not v.verify_one(pk, m + b"!", sig)

    def test_host_device_agree(self):
        triples = _triples(9, corrupt={0, 8})
        host = HostBatchVerifier().verify_batch(triples)
        dev = DeviceBatchVerifier(min_device_batch=1).verify_batch(triples)
        assert (host == dev).all()


class TestTreeHasher:
    def test_device_root_matches_host(self):
        items = [b"item-%d" % i for i in range(13)]
        assert TreeHasher("device", min_device_leaves=2).root_from_items(items) == simple_hash_from_byte_slices(items)

    def test_root_from_hashes(self):
        from tendermint_tpu.merkle.simple import leaf_hash

        hashes = [leaf_hash(b"x%d" % i) for i in range(7)]
        assert TreeHasher("device", min_device_leaves=2).root_from_hashes(hashes) == simple_hash_from_hashes(hashes)
        assert TreeHasher("host").root_from_hashes(hashes) == simple_hash_from_hashes(hashes)

    def test_ripemd_device_tree_matches_host(self):
        # the reference's bit-compat tree variant now runs on device too
        th = TreeHasher("device", algo="ripemd160", min_device_leaves=2)
        items = [b"item-%d" % i for i in range(11)]
        assert th.root_from_items(items) == simple_hash_from_byte_slices(items, "ripemd160")
        # already-hashed aggregation rides the device tree too
        from tendermint_tpu.merkle.simple import leaf_hash

        hashes = [leaf_hash(b"h%d" % i, "ripemd160") for i in range(5)]
        assert th.root_from_hashes(hashes) == simple_hash_from_hashes(hashes, "ripemd160")

    def test_edge_counts(self):
        th = TreeHasher("device", min_device_leaves=2)
        assert th.root_from_items([]) == b""
        assert th.root_from_items([b"one"]) == simple_hash_from_byte_slices([b"one"])


class TestIncrementalTableBuild:
    def test_valset_diff_rebuilds_only_changed_columns(self, monkeypatch):
        """Swapping 1 validator of 8 must build tables for exactly the
        1 new key (unchanged columns gathered from the cached set) and
        verify correctly right away (VERDICT r3 #3; EndBlock diffs touch
        few keys, reference state/execution.go:120-159)."""
        import tendermint_tpu.services.verifier as svc
        from tendermint_tpu.ops import ed25519_tables as tb
        from tendermint_tpu.services import TableBatchVerifier

        built_counts: list[int] = []
        _orig_host = tb.host_build_key_tables

        def counting_host_build(pubs):
            built_counts.append(len(pubs))
            return _orig_host([bytes(pk) for pk in pubs])

        # full builds route through the (device) build_key_tables; back
        # both builders with the host builder to keep the test
        # device-free while counting how many keys get built
        monkeypatch.setattr(
            tb, "build_key_tables", lambda arr: counting_host_build(list(arr))
        )
        monkeypatch.setattr(tb, "host_build_key_tables", counting_host_build)
        assert svc is not None  # imported for monkeypatch targets

        n = 8
        privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
        pubs = [p.pub_key.data for p in privs]
        v = TableBatchVerifier(min_device_batch=1)

        def commit_for(privs_, pubs_):
            msgs = [b"vote-%d" % i for i in range(len(privs_))]
            sigs = [p.sign(m) for p, m in zip(privs_, msgs)]
            return v.verify_commits(pubs_, [(msgs, sigs)])

        out = commit_for(privs, pubs)
        assert out.all()
        assert built_counts == [n]  # full build of all 8

        # rotate validator 3 out, a brand-new key in
        new_priv = gen_priv_key(b"\x99" * 32)
        privs2 = list(privs)
        privs2[3] = new_priv
        pubs2 = [p.pub_key.data for p in privs2]
        out2 = commit_for(privs2, pubs2)
        assert out2.all()
        assert built_counts == [n, 1]  # incremental: only the new key

        # the incremental tables are bit-identical to a from-scratch build
        inc_tables, inc_ok = v._tables_for(tuple(pubs2))
        full_tables, full_ok = _orig_host(pubs2)
        np.testing.assert_array_equal(np.asarray(inc_tables), full_tables)
        assert inc_ok.tolist() == full_ok.tolist()

    def test_prebuild_warms_cache_async(self, monkeypatch):
        from tendermint_tpu.ops import ed25519_tables as tb
        from tendermint_tpu.services import TableBatchVerifier

        _orig_host = tb.host_build_key_tables
        monkeypatch.setattr(
            tb,
            "build_key_tables",
            lambda arr: _orig_host([bytes(pk) for pk in arr]),
        )
        privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(4)]
        pubs = [p.pub_key.data for p in privs]
        v = TableBatchVerifier(min_device_batch=1)
        v.prebuild(pubs)
        import time

        deadline = time.time() + 30
        key = v._cache_key(tuple(pubs))
        while time.time() < deadline and key not in v._tables:
            time.sleep(0.05)
        assert key in v._tables


class TestShardedVerify:
    def test_verify_and_tally_on_8_device_mesh(self):
        import jax

        from tendermint_tpu.ops.ed25519_kernel import prepare_batch
        from tendermint_tpu.parallel.mesh import (
            batch_mesh,
            pad_to_multiple,
            sharded_verify_and_tally,
        )

        assert len(jax.devices()) == 8, "conftest must force the 8-device cpu mesh"
        triples = _triples(10, corrupt={3})
        pubs, msgs, sigs = (list(x) for x in zip(*triples))
        pub, r, s, h, pre = prepare_batch(pubs, msgs, sigs)
        powers = np.full(10, 5, dtype=np.int32)
        arrs, powers, valid = pad_to_multiple([pub, r, s, h], powers, 8)
        step = sharded_verify_and_tally(batch_mesh())
        ok, total = step(*arrs, powers)
        ok = np.asarray(ok)[:valid]
        assert ok.tolist() == [True] * 3 + [False] + [True] * 6
        assert int(total) == 45  # 9 valid * power 5

    def test_distributed_seam_single_process(self):
        """The multi-host seam (parallel/distributed.py) must compose
        with the sharded verify step degenerately on one process: same
        initialize/global-mesh/host_local_to_global calls a multi-host
        deployment makes (SURVEY §5.8)."""
        import jax
        from jax.sharding import PartitionSpec as P

        from tendermint_tpu.ops.ed25519_kernel import prepare_batch
        from tendermint_tpu.parallel import distributed as dist
        from tendermint_tpu.parallel.mesh import (
            BATCH_AXIS,
            pad_to_multiple,
            sharded_verify_and_tally,
        )

        dist.initialize()  # single-process no-op
        assert dist.process_info() == (0, 1)
        mesh = dist.global_batch_mesh()
        assert mesh.devices.size == 8

        triples = _triples(8, corrupt={2})
        pubs, msgs, sigs = (list(x) for x in zip(*triples))
        pub, r, s, h, _pre = prepare_batch(pubs, msgs, sigs)
        powers = np.full(8, 2, dtype=np.int32)
        arrs, powers, valid = pad_to_multiple([pub, r, s, h], powers, 8)
        spec = P(BATCH_AXIS)
        placed = [dist.host_local_to_global(mesh, spec, a) for a in arrs]
        pw = dist.host_local_to_global(mesh, spec, powers)
        ok, total = sharded_verify_and_tally(mesh)(*placed, pw)
        ok = np.asarray(ok)[:valid]
        assert ok.tolist() == [True, True, False, True, True, True, True, True]
        assert int(total) == 2 * 7

    def test_tables_path_on_8_device_mesh(self):
        """The production TABLE fast path sharded along the validator
        axis: each device holds 1/8 of the comb-table columns and the
        lanes of its own validators; a planted bad signature must
        localize and the psum power tally must exclude it."""
        import jax

        from tendermint_tpu.ops.ed25519_tables import (
            host_build_key_tables,
            prepare_commit_lanes,
        )
        from tendermint_tpu.parallel.mesh import (
            batch_mesh,
            shard_lanes_validator_major,
            sharded_tables_verify_and_tally,
            unshard_lanes_validator_major,
        )

        assert len(jax.devices()) == 8
        n_vals, k = 16, 2
        privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(n_vals)]
        pubs = [p.pub_key.data for p in privs]
        commits = []
        for c in range(k):
            msgs = [b"commit-%d-val-%d" % (c, i) for i in range(n_vals)]
            sigs = [p.sign(m) for p, m in zip(privs, msgs)]
            commits.append((msgs, sigs))
        # plant a bad signature: commit 1, validator 5
        msgs1, sigs1 = commits[1]
        sigs1[5] = sigs1[5][:10] + bytes([sigs1[5][10] ^ 1]) + sigs1[5][11:]

        tables, key_ok = host_build_key_tables(pubs)
        assert key_ok.all()
        s, h, r, pre = prepare_commit_lanes(pubs, commits)
        assert pre.all()
        lane_ok = pre & np.tile(key_ok, k)
        # non-uniform powers: proves lane/power alignment survives the
        # shard-major reorder (uniform powers would mask a mispairing)
        powers = (1 + np.arange(k * n_vals, dtype=np.int32)) % 7 + 1
        s, h, r, lane_ok, powers = shard_lanes_validator_major(
            [s, h, r, lane_ok, powers], n_vals, 8
        )

        step = sharded_tables_verify_and_tally(batch_mesh())
        ok, total = step(tables, s, h, r, lane_ok, powers)
        ok = unshard_lanes_validator_major(np.asarray(ok), n_vals, 8)
        expect = np.ones(k * n_vals, dtype=bool)
        expect[1 * n_vals + 5] = False
        assert ok.tolist() == expect.tolist()
        powers_cm = unshard_lanes_validator_major(powers, n_vals, 8)
        assert int(total) == int(powers_cm[expect].sum())


class TestFusedPathShaping:
    """Always-on gate for TableBatchVerifier.verify_commits' chunk/pad
    logic (VERDICT r4 weak #7): K not a multiple of 8, padded absent-vote
    tails, bad signatures adjacent to the pad, and chunking across
    MAX_FUSED_STACK — all on the CPU mesh via force_fused, independent of
    the kernel-marked pallas suites."""

    def _verifier_with_tables(self, n):
        import jax.numpy as jnp

        from tendermint_tpu.ops.ed25519_tables import host_build_key_tables
        from tendermint_tpu.services import TableBatchVerifier

        privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(n)]
        pubs = tuple(p.pub_key.data for p in privs)
        v = TableBatchVerifier(min_device_batch=1)
        tables, ok = host_build_key_tables(list(pubs))
        v._tables[v._cache_key(pubs)] = (pubs, jnp.asarray(tables), ok)
        return privs, pubs, v

    def _commits(self, privs, k, corrupt=(), absent=()):
        n = len(privs)
        expected = np.zeros((k, n), dtype=bool)
        commits = []
        for ci in range(k):
            msgs, sigs = [], []
            for vi, p in enumerate(privs):
                if (ci, vi) in absent:
                    msgs.append(None)
                    sigs.append(None)
                    continue
                m = b"commit-%d-vote-%d" % (ci, vi)
                s = p.sign(m)
                if (ci, vi) in corrupt:
                    s = s[:4] + bytes([s[4] ^ 1]) + s[5:]
                else:
                    expected[ci, vi] = True
                msgs.append(m)
                sigs.append(s)
            commits.append((msgs, sigs))
        return commits, expected

    def test_pad_and_chunk_boundaries(self, monkeypatch):
        import tendermint_tpu.ops.ed25519_tables as tbl_mod

        # shrink the VMEM stack bound so chunking triggers at tiny K
        monkeypatch.setattr(tbl_mod, "MAX_FUSED_STACK", 8)
        seen = []
        real_prep = tbl_mod.prepare_commit_lanes
        monkeypatch.setattr(
            tbl_mod,
            "prepare_commit_lanes",
            lambda pubs, part: (seen.append(len(part)), real_prep(pubs, part))[1],
        )

        privs, pubs, v = self._verifier_with_tables(8)
        # K=13: chunk [8] + [5 -> padded to 8]; bad sigs at the chunk
        # boundary (ci=7) and in the LAST REAL commit right against the
        # padded tail (ci=12); absent votes sprinkled in both chunks
        commits, expected = self._commits(
            privs,
            13,
            corrupt={(0, 0), (7, 7), (12, 3)},
            absent={(2, 5), (12, 7)},
        )
        got = v.verify_commits(pubs, commits, force_fused=True)
        assert got.shape == (13, 8)
        assert (got == expected).all()
        assert seen == [8, 8]  # second chunk padded 5 -> 8

    def _spy_prep_fake_kernel(self, monkeypatch):
        """Record prepare_commit_lanes part sizes and replace the device
        kernel with all-True lanes — these tests assert SHAPING decisions
        (pad/no-pad) and mask plumbing, not curve math (covered above and
        in the kernel tier), so skip the XLA compile."""
        import tendermint_tpu.ops.ed25519_tables as tbl_mod

        seen = []
        real_prep = tbl_mod.prepare_commit_lanes
        monkeypatch.setattr(
            tbl_mod,
            "prepare_commit_lanes",
            lambda pubs, part: (seen.append(len(part)), real_prep(pubs, part))[1],
        )
        monkeypatch.setattr(
            tbl_mod,
            "verify_tables_kernel",
            lambda tables, s, h, r: np.ones(s.shape[0], dtype=bool),
        )
        return seen

    def test_unfusable_shape_takes_single_launch(self, monkeypatch):
        seen = self._spy_prep_fake_kernel(monkeypatch)
        privs, pubs, v = self._verifier_with_tables(5)
        commits, presence = self._commits(privs, 3, absent={(1, 4), (2, 0)})
        got = v.verify_commits(pubs, commits)  # auto: cpu backend, no pad
        assert (got == presence).all()  # absent lanes masked by precheck
        assert seen == [3]  # K stays unpadded off the fused path

    def test_k1_commit_never_padded_on_cpu(self, monkeypatch):
        """ADVICE r4 (medium): the consensus-loop K=1 commit must not be
        shaped for the fused kernel when fused can't or shouldn't run."""
        seen = self._spy_prep_fake_kernel(monkeypatch)
        privs, pubs, v = self._verifier_with_tables(8)
        commits, presence = self._commits(privs, 1, absent={(0, 6)})
        got = v.verify_commits(pubs, commits)
        assert (got == presence).all()
        assert seen == [1]


class TestBulkTurnover:
    def test_large_diff_routes_to_device_build(self, monkeypatch):
        """A valset rotation larger than MAX_INCREMENTAL_KEYS must still
        build incrementally — missing keys as ONE device build call, not
        a host per-key loop or a full rebuild (VERDICT r4 item 4; the
        500-key bench shape, scaled for the CPU tier)."""
        import jax.numpy as jnp

        import tendermint_tpu.services.verifier as vmod
        from tendermint_tpu.ops.ed25519_tables import host_build_key_tables
        from tendermint_tpu.services import TableBatchVerifier

        privs = [gen_priv_key(bytes([i + 1]) * 32) for i in range(16)]
        pubs = tuple(p.pub_key.data for p in privs)
        v = TableBatchVerifier(min_device_batch=1)
        tables, ok = host_build_key_tables(list(pubs))
        v._tables[v._cache_key(pubs)] = (pubs, jnp.asarray(tables), ok)
        v.MAX_INCREMENTAL_KEYS = 4  # scale the 128-key threshold down

        device_builds = []
        import tendermint_tpu.ops.ed25519_tables as tbl_mod

        def fake_device_build(pub_arr, chunk=2048):
            # chunk-shape padding happens INSIDE build_key_tables (one
            # executable for all TPU builds), so the seam receives the
            # raw missing keys
            device_builds.append(pub_arr.shape[0])
            t, okk = host_build_key_tables([bytes(row) for row in pub_arr])
            return jnp.asarray(t), okk

        monkeypatch.setattr(tbl_mod, "build_key_tables", fake_device_build)

        # rotate 8 of 16 keys (> the scaled threshold)
        new_privs = [gen_priv_key(bytes([100 + i]) * 32) for i in range(8)]
        pubs2 = list(pubs)
        for i, np_ in enumerate(new_privs):
            pubs2[i * 2] = np_.pub_key.data
        t2, ok2 = v._tables_for(tuple(pubs2))
        assert device_builds == [8], device_builds  # one bulk device build
        assert ok2.all()

        # the assembled tables must actually verify a commit of the new set
        all_privs = {p.pub_key.data: p for p in privs + new_privs}
        msgs = [b"turnover-%d" % i for i in range(16)]
        sigs = [all_privs[pk].sign(m) for pk, m in zip(pubs2, msgs)]
        got = v.verify_commits(pubs2, [(msgs, sigs)])
        assert got.shape == (1, 16) and got.all()
