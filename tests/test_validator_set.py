import pytest

from tendermint_tpu.types import ValidationError, Validator, ValidatorSet
from tests.helpers import CHAIN_ID, make_block_id, make_commit, make_validators


def test_sorted_by_address():
    vs, _ = make_validators(10)
    addrs = [v.address for v in vs.validators]
    assert addrs == sorted(addrs)
    assert vs.total_voting_power == 100


def test_proposer_rotation_equal_power_cycles():
    vs, _ = make_validators(4)
    seen = []
    for _ in range(8):
        vs.increment_accum(1)
        seen.append(vs.proposer.address)
    # equal power: each validator proposes twice over 8 rounds
    from collections import Counter

    counts = Counter(seen)
    assert all(c == 2 for c in counts.values())


def test_proposer_rotation_weighted():
    _, privs = make_validators(3)
    vals = [
        Validator(address=p.address, pub_key=p.pub_key, voting_power=w)
        for p, w in zip(privs, [1, 1, 8])
    ]
    vs = ValidatorSet(vals)
    heavy = vals[2].address
    from collections import Counter

    seen = Counter()
    for _ in range(10):
        vs.increment_accum(1)
        seen[vs.proposer.address] += 1
    assert seen[heavy] == 8


def test_hash_changes_with_membership():
    vs, _ = make_validators(4)
    h1 = vs.hash()
    vs2, _ = make_validators(5)
    assert h1 != vs2.hash()
    assert len(h1) == 32


def test_verify_commit_ok():
    vs, privs = make_validators(4)
    bid = make_block_id()
    commit = make_commit(vs, privs, height=5, round_=0, block_id=bid)
    vs.verify_commit(CHAIN_ID, bid, 5, commit)  # no raise


def test_verify_commit_insufficient_power():
    vs, privs = make_validators(4)
    bid = make_block_id()
    # only 2 of 4 sign -> 50% < 2/3... but make_commit needs maj23; build by hand
    from tests.helpers import signed_vote
    from tendermint_tpu.types import VOTE_TYPE_PRECOMMIT, Commit

    votes = [None] * 4
    for i in range(2):
        votes[i] = signed_vote(privs[i], i, 5, 0, VOTE_TYPE_PRECOMMIT, bid)
    commit = Commit(block_id=bid, precommits=votes)
    with pytest.raises(ValidationError, match="insufficient"):
        vs.verify_commit(CHAIN_ID, bid, 5, commit)


def test_verify_commit_bad_signature():
    vs, privs = make_validators(4)
    bid = make_block_id()
    commit = make_commit(vs, privs, height=5, round_=0, block_id=bid)
    # corrupt one signature
    v = commit.precommits[0]
    commit.precommits[0] = v.with_signature(bytes(64))
    with pytest.raises(ValidationError, match="signature"):
        vs.verify_commit(CHAIN_ID, bid, 5, commit)


def test_verify_commit_wrong_height():
    vs, privs = make_validators(4)
    bid = make_block_id()
    commit = make_commit(vs, privs, height=5, round_=0, block_id=bid)
    with pytest.raises(ValidationError):
        vs.verify_commit(CHAIN_ID, bid, 6, commit)


def test_verify_commit_any_small_change():
    vs, privs = make_validators(4)
    bid = make_block_id()
    commit = make_commit(vs, privs, height=7, round_=0, block_id=bid)
    # old set == new set works through verify_commit_any too
    vs.verify_commit_any(vs, CHAIN_ID, bid, 7, commit)


def test_apply_changes():
    vs, privs = make_validators(4)
    target = vs.validators[0]
    vs.apply_changes([Validator(target.address, target.pub_key, 0)])
    assert vs.size() == 3
    assert not vs.has_address(target.address)
    # update power
    v1 = vs.validators[0]
    vs.apply_changes([Validator(v1.address, v1.pub_key, 99)])
    assert vs.get_by_address(v1.address)[1].voting_power == 99


def test_duplicate_address_rejected():
    vs, _ = make_validators(2)
    with pytest.raises(ValidationError):
        ValidatorSet(list(vs.validators) + [vs.validators[0]])


def test_verify_commit_any_requires_new_set_quorum():
    # Old set: 4 validators of 10. New set: same 4 plus a whale of 120.
    # A commit signed by the original 4 has >2/3 of OLD power but only
    # 40/160 of NEW power -> must be rejected (reference :340-346 rule).
    from tests.helpers import det_priv_keys
    from tendermint_tpu.types import PrivValidator

    vs, privs = make_validators(4)
    whale_priv = PrivValidator(det_priv_keys(5)[4])
    new_vals = list(vs.validators) + [
        Validator(whale_priv.address, whale_priv.pub_key, 120)
    ]
    new_vs = ValidatorSet(new_vals)
    bid = make_block_id()
    # commit shaped for the NEW set (5 slots), signed only by the old 4
    from tests.helpers import signed_vote
    from tendermint_tpu.types import VOTE_TYPE_PRECOMMIT, Commit

    precommits = [None] * new_vs.size()
    for i, val in enumerate(new_vs.validators):
        idx, old = vs.get_by_address(val.address)
        if old is None:
            continue
        p = next(p for p in privs if p.address == val.address)
        precommits[i] = signed_vote(p, i, 9, 0, VOTE_TYPE_PRECOMMIT, bid)
    commit = Commit(block_id=bid, precommits=precommits)
    with pytest.raises(ValidationError, match="new voting power"):
        vs.verify_commit_any(new_vs, CHAIN_ID, bid, 9, commit)
