"""Light client: static/dynamic/inquiring certifiers + providers
(reference `certifiers/*_test.go`; BASELINE config 2 batched replay).
"""

import pytest

from tendermint_tpu.certifiers import (
    DynamicCertifier,
    FileProvider,
    FullCommit,
    InquiringCertifier,
    MemProvider,
    StaticCertifier,
)
from tendermint_tpu.crypto import PrivKey
from tendermint_tpu.types import PrivValidator, Validator, ValidatorSet
from tendermint_tpu.types.block import Header
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.errors import (
    ErrTooMuchChange,
    ErrValidatorsChanged,
    ValidationError,
)
from tendermint_tpu.types.part_set import PartSetHeader

from tests.helpers import make_commit

CHAIN = "light-chain"


def _privs(indices):
    return [PrivValidator(PrivKey(i.to_bytes(32, "little"))) for i in indices]


def _valset(privs, power=10):
    return ValidatorSet(
        [Validator(address=p.address, pub_key=p.pub_key, voting_power=power) for p in privs]
    )


def _full_commit(height, privs, app_hash=b"app"):
    """FullCommit at `height` signed by `privs`' valset."""
    vs = _valset(privs)
    header = Header(
        chain_id=CHAIN,
        height=height,
        time=height * 1_000_000_000,
        num_txs=0,
        last_block_id=BlockID.zero(),
        last_commit_hash=b"",
        data_hash=b"",
        validators_hash=vs.hash(),
        app_hash=app_hash,
    )
    block_id = BlockID(header.hash(), PartSetHeader(total=1, hash=header.hash()[:20]))
    ordered = sorted(privs, key=lambda p: p.address)
    commit = make_commit(vs, ordered, height, 0, block_id, CHAIN)
    return FullCommit(header=header, commit=commit, validators=vs)


class TestStaticCertifier:
    def test_certify_and_batch(self):
        privs = _privs(range(1, 5))
        fcs = [_full_commit(h, privs) for h in (5, 6, 7)]
        cert = StaticCertifier(CHAIN, _valset(privs))
        cert.certify(fcs[0])
        cert.certify_batch(fcs)  # config-2 shape: K commits, one call

    def test_rejects_wrong_chain_and_forged_sig(self):
        privs = _privs(range(1, 5))
        fc = _full_commit(3, privs)
        with pytest.raises(ValidationError, match="chain"):
            StaticCertifier("other", _valset(privs)).certify(fc)
        # forge one signature
        bad = fc.commit.precommits[1]
        sig = bytearray(bad.signature)
        sig[5] ^= 1
        fc.commit.precommits[1] = bad.with_signature(bytes(sig))
        with pytest.raises(ValidationError, match="validator 1"):
            StaticCertifier(CHAIN, _valset(privs)).certify(fc)

    def test_validators_changed_is_typed(self):
        fc = _full_commit(3, _privs(range(1, 5)))
        other = _valset(_privs(range(10, 14)))
        with pytest.raises(ErrValidatorsChanged):
            StaticCertifier(CHAIN, other).certify(fc)


class TestDynamicCertifier:
    def test_update_follows_small_change(self):
        old = _privs([1, 2, 3, 4])
        new = _privs([1, 2, 3, 5])  # one of four replaced: 75% overlap
        cert = DynamicCertifier(CHAIN, _valset(old), height=1)
        fc = _full_commit(10, new)
        cert.update(fc)
        assert cert.last_height == 10
        cert.certify(_full_commit(11, new))

    def test_update_rejects_large_change(self):
        old = _privs([1, 2, 3, 4])
        new = _privs([1, 2, 5, 6])  # half replaced: 50% < 2/3
        cert = DynamicCertifier(CHAIN, _valset(old), height=1)
        with pytest.raises(ErrTooMuchChange):
            cert.update(_full_commit(10, new))

    def test_update_height_must_increase(self):
        privs = _privs([1, 2, 3, 4])
        cert = DynamicCertifier(CHAIN, _valset(privs), height=10)
        with pytest.raises(ValidationError, match="height"):
            cert.update(_full_commit(5, privs))


class TestInquiringCertifier:
    def _chain(self):
        """heights 1..4 rotate one validator each: any 2-step jump
        changes half the set (> 1/3), forcing bisection."""
        sets = {
            1: _privs([1, 2, 3, 4]),
            2: _privs([1, 2, 3, 5]),
            3: _privs([1, 2, 5, 6]),
            4: _privs([1, 5, 6, 7]),
        }
        return {h: _full_commit(h, p) for h, p in sets.items()}

    def test_bisection_across_large_total_change(self):
        fcs = self._chain()
        source = MemProvider()
        for fc in fcs.values():
            source.store_commit(fc)
        trusted = MemProvider()
        inq = InquiringCertifier(CHAIN, fcs[1], trusted, source)
        # direct 1->4 changed 3 of 4 validators; must bisect via 2 and 3
        inq.certify(fcs[4])
        assert inq.cert.last_height == 4
        # intermediate hops became trusted
        assert trusted.get_by_height(3).height() >= 2

    def test_fails_without_intermediate_commits(self):
        fcs = self._chain()
        source = MemProvider()
        source.store_commit(fcs[1])
        source.store_commit(fcs[4])  # gap: no 2, 3
        inq = InquiringCertifier(CHAIN, fcs[1], MemProvider(), source)
        with pytest.raises(ErrTooMuchChange):
            inq.certify(fcs[4])

    def test_same_valset_certifies_without_update(self):
        privs = _privs([1, 2, 3, 4])
        seed = _full_commit(1, privs)
        inq = InquiringCertifier(CHAIN, seed, MemProvider(), MemProvider())
        inq.certify(_full_commit(7, privs))


class TestProviders:
    def test_mem_provider_floor_lookup(self):
        p = MemProvider()
        privs = _privs([1, 2, 3, 4])
        for h in (2, 5, 9):
            p.store_commit(_full_commit(h, privs))
        assert p.get_by_height(1) is None
        assert p.get_by_height(5).height() == 5
        assert p.get_by_height(8).height() == 5
        assert p.latest_commit().height() == 9

    def test_file_provider_round_trip(self, tmp_path):
        p = FileProvider(str(tmp_path / "trust"))
        privs = _privs([1, 2, 3, 4])
        fc = _full_commit(12, privs)
        p.store_commit(fc)
        # fresh instance reads the same directory (restart survival)
        p2 = FileProvider(str(tmp_path / "trust"))
        got = p2.get_by_height(100)
        assert got.height() == 12
        assert got.header.hash() == fc.header.hash()
        assert got.validators.hash() == fc.validators.hash()
        # decoded commit still certifies
        StaticCertifier(CHAIN, got.validators).certify(got)
