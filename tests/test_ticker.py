"""AdaptiveTimeouts (consensus/ticker.py): measured-latency timeout
derivation — clamping to configured ceilings, cold-start fallback to
the fixed ladder, and byzantine arrival outliers never inflating the
derived values past the configured fixed timeouts."""

import pytest

from tendermint_tpu.consensus.config import ConsensusConfig
from tendermint_tpu.consensus.ticker import AdaptiveTimeouts
from tendermint_tpu.telemetry import heightlog


def _ledger_with_phases(n, propose_s=0.010, prevote_s=0.005, precommit_s=0.005):
    led = heightlog.HeightLedger()
    for h in range(1, n + 1):
        led.record(
            {
                "height": h,
                "phases": {
                    "propose": {"s": propose_s},
                    "prevote": {"s": prevote_s},
                    "precommit": {"s": precommit_s},
                },
            }
        )
    return led


def _rollup(peer_delays: dict):
    """peer -> list of observed arrival delays (seconds)."""
    r = heightlog.VoteArrivalRollup()
    for peer, delays in peer_delays.items():
        for d in delays:
            r.observe(peer, d)
    return r


class TestAdaptiveTimeouts:
    def test_cold_start_falls_back_to_fixed(self):
        cfg = ConsensusConfig()  # adaptive on by default
        at = AdaptiveTimeouts(cfg, rollup=_rollup({}), ledger=heightlog.HeightLedger())
        # empty rollup + empty ledger: every phase sleeps the fixed ladder
        assert at.propose_timeout(0) == cfg.propose_timeout(0)
        assert at.prevote_timeout(0) == cfg.prevote_timeout(0)
        assert at.precommit_timeout(0) == cfg.precommit_timeout(0)
        assert at.commit_timeout() == cfg.commit_timeout()

    def test_too_few_heights_falls_back(self):
        cfg = ConsensusConfig()
        led = _ledger_with_phases(AdaptiveTimeouts.MIN_HEIGHTS - 1)
        at = AdaptiveTimeouts(cfg, rollup=_rollup({"p1": [0.001]}), ledger=led)
        assert at.propose_timeout(0) == cfg.propose_timeout(0)
        assert at.commit_timeout() == cfg.commit_timeout()

    def test_derivation_engages_and_floors(self):
        cfg = ConsensusConfig(timeout_derived_floor=2)
        led = _ledger_with_phases(16, propose_s=0.010)
        rollup = _rollup({f"p{i}": [0.001] * 4 for i in range(4)})
        at = AdaptiveTimeouts(cfg, rollup=rollup, ledger=led)
        # propose: p95 of 10ms phase * SAFETY(3) = 30ms, under the 3000ms fixed
        assert at.propose_timeout(0) == pytest.approx(0.030, rel=0.01)
        # commit: 1ms median-of-means * 3 = 3ms, over the 2ms floor
        assert at.commit_timeout() == pytest.approx(0.003, rel=0.01)
        # floor: sub-floor measurements can't spin the ticker
        tiny = _rollup({f"p{i}": [0.0001] for i in range(4)})
        at_tiny = AdaptiveTimeouts(cfg, rollup=tiny, ledger=led)
        assert at_tiny.commit_timeout() == pytest.approx(0.002, rel=0.01)

    def test_clamped_to_configured_ceiling(self):
        """Inflated measurements (e.g. every peer slow) derive AT MOST
        the configured fixed value — the operator's ladder is a hard
        ceiling, not a suggestion."""
        cfg = ConsensusConfig()
        led = _ledger_with_phases(
            16, propose_s=900.0, prevote_s=900.0, precommit_s=900.0
        )
        rollup = _rollup({f"p{i}": [50.0] * 4 for i in range(4)})
        at = AdaptiveTimeouts(cfg, rollup=rollup, ledger=led)
        assert at.propose_timeout(0) == cfg.propose_timeout(0)
        assert at.prevote_timeout(1) == cfg.prevote_timeout(1)
        assert at.commit_timeout() == cfg.commit_timeout()

    def test_byzantine_outlier_cannot_inflate(self):
        """One peer stamping absurd vote timestamps (delays clamped to
        MAX_ARRIVAL_S at observation) moves nothing: the estimate is
        the median of per-peer means, so a minority of liars is
        ignored entirely."""
        cfg = ConsensusConfig()
        led = _ledger_with_phases(16)
        honest = {f"p{i}": [0.002] * 8 for i in range(4)}
        at_honest = AdaptiveTimeouts(cfg, rollup=_rollup(honest), ledger=led)
        baseline = at_honest.commit_timeout()
        poisoned = dict(honest)
        poisoned["byz"] = [heightlog.MAX_ARRIVAL_S] * 64
        at_poisoned = AdaptiveTimeouts(cfg, rollup=_rollup(poisoned), ledger=led)
        assert at_poisoned.commit_timeout() == pytest.approx(baseline, rel=0.01)

    def test_opt_out_config_and_env(self, monkeypatch):
        led = _ledger_with_phases(16, propose_s=0.010)
        rollup = _rollup({f"p{i}": [0.001] * 4 for i in range(4)})
        cfg_off = ConsensusConfig(adaptive_timeouts=False)
        at = AdaptiveTimeouts(cfg_off, rollup=rollup, ledger=led)
        assert at.propose_timeout(0) == cfg_off.propose_timeout(0)
        cfg_on = ConsensusConfig()
        monkeypatch.setenv("TENDERMINT_TPU_ADAPTIVE_TIMEOUTS", "0")
        at_env = AdaptiveTimeouts(cfg_on, rollup=rollup, ledger=led)
        assert at_env.propose_timeout(0) == cfg_on.propose_timeout(0)

    def test_derived_gauge_exported(self):
        from tendermint_tpu.telemetry import REGISTRY

        cfg = ConsensusConfig()
        led = _ledger_with_phases(16, propose_s=0.010)
        at = AdaptiveTimeouts(cfg, rollup=_rollup({}), ledger=led)
        at.propose_timeout(0)
        fam = REGISTRY.get("tendermint_consensus_timeout_derived_seconds")
        vals = {labels[0]: snap for labels, snap in fam.samples()}
        assert vals["propose"] == pytest.approx(0.030, rel=0.01)
