"""Full-node composition: CLI init/testnet, solo chain over RPC,
multi-node TCP testnet, kill -9 crash recovery (reference
`node/node_test.go`, `cmd/`, `test/p2p/`, `test/persist/`).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from tendermint_tpu.cmd import main as cli_main
from tendermint_tpu.config import Config, load_config
from tendermint_tpu.node import Node

pytestmark = pytest.mark.slow


def rpc(port, method, **params):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=90) as resp:
        out = json.load(resp)
    if "error" in out:
        raise RuntimeError(out["error"])
    return out["result"]


def wait_until(pred, timeout=60.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


class TestCLI:
    def test_init_creates_home(self, tmp_path):
        home = str(tmp_path / "home")
        assert cli_main(["init", "--home", home, "--chain-id", "cli-chain"]) == 0
        for f in ("config.toml", "genesis.json", "priv_validator.json"):
            assert os.path.exists(os.path.join(home, f))
        cfg = load_config(home)
        assert cfg.base.moniker  # toml round-trips

    def test_testnet_generates_wired_homes(self, tmp_path):
        out = str(tmp_path / "net")
        assert (
            cli_main(
                ["testnet", "--n", "3", "--output", out, "--starting-port", "47000"]
            )
            == 0
        )
        gens = set()
        for i in range(3):
            cfg = load_config(os.path.join(out, f"node{i}"))
            assert cfg.p2p.seeds.count(":") == 2  # two peer addrs
            with open(os.path.join(out, f"node{i}", "genesis.json")) as f:
                gens.add(f.read())
        assert len(gens) == 1  # identical genesis everywhere


def _solo_node(tmp_path, fast_sync=False) -> Node:
    home = str(tmp_path / "solo")
    cli_main(["init", "--home", home, "--chain-id", "solo-test"])
    cfg = Config.test_config(home)
    cfg.base.fast_sync = fast_sync
    node = Node(cfg)
    node.start()
    return node


class TestSoloNode:
    def test_commits_and_serves_rpc(self, tmp_path):
        node = _solo_node(tmp_path)
        try:
            port = node.rpc_port
            tx = b"rpc-key=rpc-val".hex()
            res = rpc(port, "broadcast_tx_commit", tx=tx)
            assert res["deliver_tx"]["code"] == 0
            assert res["height"] >= 1
            status = rpc(port, "status")
            assert status["sync_info"]["latest_block_height"] >= res["height"] - 1
            q = rpc(port, "abci_query", path="", data=b"rpc-key".hex())
            assert bytes.fromhex(q["value"]) == b"rpc-val"
            blk = rpc(port, "block", height=res["height"])
            assert blk["block"]["header"]["height"] == res["height"]
            vals = rpc(port, "validators")
            assert len(vals["validators"]) == 1
            found = rpc(port, "tx", hash=res["hash"])
            assert bytes.fromhex(found["tx"]) == b"rpc-key=rpc-val"
        finally:
            node.stop()


class TestTcpTestnet:
    def test_four_nodes_over_tcp(self, tmp_path):
        out = str(tmp_path / "net")
        cli_main(
            ["testnet", "--n", "4", "--output", out, "--starting-port", "0"]
        )
        nodes = []
        try:
            # start with ephemeral ports, then dial actual addresses
            for i in range(4):
                cfg = Config.test_config(os.path.join(out, f"node{i}"))
                cfg.base.moniker = f"node{i}"
                nodes.append(Node(cfg))
            for n in nodes:
                n.start()
            from tendermint_tpu.p2p.tcp import dial

            for i in range(4):
                for j in range(i + 1, 4):
                    try:
                        dial(
                            nodes[i].switch,
                            f"127.0.0.1:{nodes[j].p2p_port}",
                            priv_key=nodes[i]._node_key,
                        )
                    except ValueError as e:
                        # event-driven PEX may have meshed the pair
                        # before this manual dial — a benign race the
                        # reference's DialSeeds also just logs
                        if "duplicate peer" not in str(e):
                            raise
            wait_until(
                lambda: all(n.block_store.height >= 3 for n in nodes),
                timeout=90,
                msg="testnet commits over TCP",
            )
            h1 = {n.block_store.load_block(1).hash() for n in nodes}
            assert len(h1) == 1
            # tx gossip: submit via node0's RPC, committed chain-wide
            res = rpc(nodes[0].rpc_port, "broadcast_tx_commit", tx=b"a=b".hex())
            assert res["deliver_tx"]["code"] == 0
            info = rpc(nodes[3].rpc_port, "net_info")
            assert info["n_peers"] == 3
        finally:
            for n in nodes:
                n.stop()


class TestPersistentPeers:
    def test_reconnects_after_peer_drop(self, tmp_path):
        """A dropped persistent peer is redialed with backoff until the
        link heals (reference `reconnectToPeer p2p/switch.go:290-320`) —
        seeds-only topologies never heal, persistent ones must."""
        out = str(tmp_path / "net")
        cli_main(["testnet", "--n", "2", "--output", out, "--starting-port", "0"])
        cfg0 = Config.test_config(os.path.join(out, "node0"))
        cfg1 = Config.test_config(os.path.join(out, "node1"))
        for c in (cfg0, cfg1):
            c.p2p.pex = False  # isolate: only the persistent logic may redial
            c.base.fast_sync = False
        n0 = Node(cfg0)
        n0.start()
        try:
            cfg1.p2p.persistent_peers = f"127.0.0.1:{n0.p2p_port}"
            cfg1.p2p.reconnect_base_backoff_s = 0.05
            n1 = Node(cfg1)
            n1.start()
            try:
                wait_until(
                    lambda: n0.switch.n_peers() == 1 and n1.switch.n_peers() == 1,
                    timeout=30,
                    msg="persistent peer connects",
                )
                # sever from the remote side: n1's conn dies, and only the
                # persistent-peer manager may bring it back
                n0.switch.stop_peer(n0.switch.peers()[0], "test drop")
                wait_until(
                    lambda: n0.switch.n_peers() == 1 and n1.switch.n_peers() == 1,
                    timeout=30,
                    msg="persistent peer reconnects after drop",
                )
            finally:
                n1.stop()
        finally:
            n0.stop()


class TestCrashRecovery:
    def test_kill9_and_restart_resumes_chain(self, tmp_path):
        home = str(tmp_path / "crash")
        cli_main(["init", "--home", home, "--chain-id", "crash-test"])

        script = (
            "import sys; sys.path.insert(0, %r); "
            "from tendermint_tpu.config import Config; "
            "from tendermint_tpu.node import Node; "
            "cfg = Config.test_config(%r); cfg.base.fast_sync = False; "
            "cfg.rpc.laddr = 'tcp://127.0.0.1:%%d' %% int(sys.argv[1]); "
            "n = Node(cfg); n.start(); print('UP', flush=True); "
            "import time; time.sleep(600)"
        ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))), home)

        errlog = open(str(tmp_path / "node_stderr.log"), "ab")

        def run(port):
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            return subprocess.Popen(
                [sys.executable, "-c", script, str(port)],
                stdout=subprocess.PIPE,
                stderr=errlog,
                env=env,
            )

        import socket as socket_mod

        with socket_mod.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        def height_or_none():
            # the freshly-(re)started subprocess may not serve RPC yet;
            # transient connection errors are part of the wait
            try:
                return rpc(port, "status")["sync_info"]["latest_block_height"]
            except Exception:
                return None

        proc = run(port)
        try:
            assert proc.stdout.readline().strip() == b"UP"
            wait_until(
                lambda: (height_or_none() or 0) >= 2,
                timeout=90,
                msg="first run commits",
            )
            h_before = rpc(port, "status")["sync_info"]["latest_block_height"]
        finally:
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait()

        proc = run(port)
        try:
            assert proc.stdout.readline().strip() == b"UP"
            wait_until(
                lambda: (height_or_none() or 0) >= h_before + 2,
                timeout=90,
                msg="chain resumes past pre-crash height",
            )
        finally:
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait()
