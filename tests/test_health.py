"""`/health` endpoint + health snapshot: unit coverage of the status
derivation, then the nemesis-driven state transitions asserted ON THE
ENDPOINT (not internals): breaker trip → degraded, mesh shrink →
degraded, heal/re-probe → ok, fresh fast-syncing joiner → not_ready."""

import json
import os
import sys
import time
import urllib.error
import urllib.request
from types import SimpleNamespace

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.services.resilient import ResilientVerifier
from tendermint_tpu.services.verifier import HostBatchVerifier
from tendermint_tpu.telemetry.health import build_health
from tendermint_tpu.telemetry.heightlog import HeightLedger
from tendermint_tpu.utils import fail
from tendermint_tpu.utils.circuit import CircuitBreaker


def _get_health(port: int):
    """(http_status, body) for GET /health — 503 must carry the body
    too (load balancers read the code, operators read the JSON)."""
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/health", timeout=10
        ) as resp:
            return resp.status, json.load(resp)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def _rpc(port, method, **params):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        out = json.load(resp)
    if "error" in out:
        raise RuntimeError(out["error"])
    return out["result"]


def _wait_status(port: int, want: str, timeout: float = 30.0) -> dict:
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        _code, body = _get_health(port)
        last = body
        if body["status"] == want:
            return body
        time.sleep(0.1)
    raise AssertionError(f"health never reached {want!r}; last: {last}")


def _stub_node(**over):
    """Minimal duck-typed node for build_health: every field the
    checks getattr their way into."""
    ledger = over.pop("ledger", None)
    if ledger is None:
        ledger = HeightLedger()
        now = time.time()
        for h in (1, 2, 3):
            ledger.record(
                {"height": h, "finality_s": 0.2 if h > 1 else None, "t_commit": now}
            )
    verifier = over.pop(
        "verifier", SimpleNamespace(snapshot=lambda: {"state": "closed"})
    )
    node = SimpleNamespace(
        node_id="stub",
        consensus=SimpleNamespace(
            verifier=verifier, fatal_error=over.pop("fatal", None)
        ),
        blockchain_reactor=SimpleNamespace(
            fast_sync=over.pop("fast_sync", False)
        ),
        statesync_reactor=None,
        switch=SimpleNamespace(n_peers=lambda: over.pop("peers", 3)),
        block_store=SimpleNamespace(height=3),
        hasher=None,
        height_ledger=ledger,
    )
    return node


class TestBuildHealth:
    def test_ok(self):
        h = build_health(_stub_node())
        assert h["status"] == "ok" and h["ready"]
        assert h["checks"]["breakers"]["states"] == {"verifier": "closed"}
        assert h["finality_slo"]["window"] == 2
        assert h["finality_slo"]["ok"]

    def test_open_breaker_degrades(self):
        node = _stub_node(
            verifier=SimpleNamespace(snapshot=lambda: {"state": "open"})
        )
        h = build_health(node)
        assert h["status"] == "degraded" and h["ready"]
        assert not h["checks"]["breakers"]["ok"]

    def test_mesh_shrink_degrades(self):
        node = _stub_node(
            verifier=SimpleNamespace(
                snapshot=lambda: {
                    "state": "closed",
                    "mesh": {"devices_active": 7, "devices_total": 8},
                }
            )
        )
        h = build_health(node)
        assert h["status"] == "degraded"
        assert not h["checks"]["mesh"]["ok"]
        assert h["checks"]["mesh"]["devices_active"] == 7

    def test_zero_peers_degrades(self):
        h = build_health(_stub_node(peers=0))
        assert h["status"] == "degraded"
        assert not h["checks"]["peers"]["ok"]

    def test_fast_sync_not_ready(self):
        h = build_health(_stub_node(fast_sync=True))
        assert h["status"] == "not_ready" and not h["ready"]

    def test_fatal_consensus_not_ready(self):
        h = build_health(_stub_node(fatal=RuntimeError("boom")))
        assert h["status"] == "not_ready"
        assert h["checks"]["consensus"]["fatal"] == "RuntimeError"

    def test_stalled_commits_degrade(self):
        ledger = HeightLedger()
        ledger.record(
            {"height": 5, "finality_s": 0.2, "t_commit": time.time() - 3600}
        )
        h = build_health(_stub_node(ledger=ledger))
        assert h["status"] == "degraded"
        assert not h["checks"]["commit_lag"]["ok"]

    def test_slo_breach_reported_not_degrading(self, monkeypatch):
        """An SLO burn > 1 is an alert, not a routing decision: the
        section flips its own ok bit, the status stays ok."""
        # the boost reflex is covered by TestSloTraceBoost; keep this
        # test from arming a process-wide sampling window
        monkeypatch.setenv("TENDERMINT_TPU_SLO_BOOST_S", "0")
        ledger = HeightLedger()
        now = time.time()
        for h in range(1, 12):
            ledger.record(
                {"height": h, "finality_s": 5.0, "t_commit": now}
            )
        h = build_health(_stub_node(ledger=ledger))
        assert not h["finality_slo"]["ok"]
        assert h["finality_slo"]["breaches"] == 11
        assert h["status"] == "ok"

    def test_pipeline_section_reported_not_folded(self):
        """The cross-height pipeline state rides the reported-never-
        folded convention: apply-in-flight and stall counts appear, the
        status does not move."""
        node = _stub_node()
        node.consensus.pipeline_enabled = True
        node.consensus._pending_apply = {"height": 3}
        node.consensus.pipeline_stats = {
            "joins": 4,
            "stalls": 3,  # stall-heavy: apply dominates — still "ok"
            "valset_rebuilds": 1,
            "overlap_s_total": 0.08,
            "last_overlap_s": 0.02,
        }
        h = build_health(node)
        assert h["status"] == "ok"
        p = h["pipeline"]
        assert p["enabled"] and p["apply_in_flight"]
        assert p["joins"] == 4 and p["stalls"] == 3
        assert p["valset_rebuilds"] == 1
        assert p["last_overlap_ms"] == pytest.approx(20.0)
        assert p["overlap_ms_mean"] == pytest.approx(20.0)

    def test_pipeline_section_tolerates_stub(self):
        # a consensus stub without pipeline fields still health-checks
        h = build_health(_stub_node())
        assert h["pipeline"]["enabled"] is False
        assert h["pipeline"]["apply_in_flight"] is False

    def test_empty_ledger_is_ok(self):
        led = HeightLedger()
        h = build_health(_stub_node(ledger=led))
        assert h["status"] == "ok"
        assert h["finality_slo"]["window"] == 0


class TestSloTraceBoost:
    """Budget exhaustion arms the trace-sampling boost window — the
    breaker-trip reflex applied to finality (PR 12 satellite)."""

    @pytest.fixture(autouse=True)
    def _reset_boost(self):
        from tendermint_tpu.telemetry import tracectx as tc

        tc._boost_until = 0.0
        yield
        tc._boost_until = 0.0

    def _breaching_ledger(self):
        ledger = HeightLedger()
        now = time.time()
        for h in range(1, 12):
            ledger.record({"height": h, "finality_s": 5.0, "t_commit": now})
        return ledger

    def test_breach_lights_up_tracing(self, monkeypatch):
        from tendermint_tpu.telemetry import tracectx as tc

        monkeypatch.setenv("TENDERMINT_TPU_SLO_BOOST_S", "5")
        assert not tc.sampling_forced()
        h = build_health(_stub_node(ledger=self._breaching_ledger()))
        assert not h["finality_slo"]["ok"]
        assert h["finality_slo"]["trace_boosted"] is True
        assert tc.sampling_forced()
        # boosted sampling mints even at rate 0 (the boost semantics
        # breaker trips rely on — same path, now armed by the SLO)
        monkeypatch.setenv(tc.SAMPLE_ENV, "0")
        assert tc.mint("slo-boost-test") is not None

    def test_healthy_window_does_not_boost(self, monkeypatch):
        from tendermint_tpu.telemetry import tracectx as tc

        monkeypatch.setenv("TENDERMINT_TPU_SLO_BOOST_S", "5")
        h = build_health(_stub_node())
        assert h["finality_slo"]["ok"]
        assert "trace_boosted" not in h["finality_slo"]
        assert not tc.sampling_forced()

    def test_boost_knob_zero_disables(self, monkeypatch):
        from tendermint_tpu.telemetry import tracectx as tc

        monkeypatch.setenv("TENDERMINT_TPU_SLO_BOOST_S", "0")
        h = build_health(_stub_node(ledger=self._breaching_ledger()))
        assert not h["finality_slo"]["ok"]
        assert "trace_boosted" not in h["finality_slo"]
        assert not tc.sampling_forced()


def _resilient_factory(threshold=2, reset_s=0.5):
    def factory(_i):
        return ResilientVerifier(
            HostBatchVerifier(),
            breaker=CircuitBreaker(
                failure_threshold=threshold, reset_timeout_s=reset_s
            ),
            max_retries=0,
        )

    return factory


class TestHealthTransitions:
    """The acceptance cycle on live full nodes, asserted via HTTP."""

    def test_breaker_cycle_and_fresh_joiner(self, tmp_path):
        from tendermint_tpu.testing.nemesis import FullNemesisNode, Nemesis

        with Nemesis(
            4,
            home=str(tmp_path),
            node_factory=Nemesis.full_node_factory(),
            verifier_factory=_resilient_factory(),
        ) as net:
            net.wait_height(2, timeout=60)
            port = net.nodes[0].rpc_port
            code, body = _get_health(port)
            assert code == 200 and body["status"] == "ok", body

            # device dies mid-consensus -> breaker trips -> degraded
            fail.set_device_fault("verify")
            try:
                net.wait_progress(delta=1, timeout=60)
                body = _wait_status(port, "degraded", timeout=30)
                assert not body["checks"]["breakers"]["ok"], body
                assert body["ready"]  # degraded still serves
            finally:
                fail.clear_device_faults()

            # heal: breaker re-probes closed -> ok again
            body = _wait_status(port, "ok", timeout=30)
            assert body["checks"]["breakers"]["states"]["verifier"] == "closed"

            # the SLO window is live on a committing chain
            assert body["finality_slo"]["window"] > 0

            # dump_telemetry serves the ledger + per-peer vote arrivals
            dump = _rpc(port, "dump_telemetry", heights=4)
            assert dump["heights"] and dump["heights"][-1]["critical_path"]
            assert dump["vote_arrivals"]

            # fresh joiner: fast-syncing (no peers yet, nothing synced)
            # -> not_ready with HTTP 503; after catching up -> ready/ok
            joiner = FullNemesisNode(
                4, net.genesis, net.privs, net.home, net.chain_id
            )
            joiner.start()
            code, body = _get_health(joiner.rpc_port)
            assert code == 503, body
            assert body["status"] == "not_ready" and body["catching_up"]
            net.add_node(joiner)
            target = net.nodes[0].store.height + 2
            net.wait_height(target, timeout=90)
            body = _wait_status(joiner.rpc_port, "ok", timeout=30)
            assert body["ready"] and not body["catching_up"]

    def test_mesh_shrink_and_restore_cycle(self, tmp_path):
        from tendermint_tpu.parallel.mesh import MeshManager
        from tendermint_tpu.services.batcher import CoalescingVerifier
        from tendermint_tpu.services.verifier import ShardedBatchVerifier
        from tendermint_tpu.testing.nemesis import Nemesis

        def factory(_i):
            return CoalescingVerifier(
                ResilientVerifier(
                    ShardedBatchVerifier(
                        mesh=MeshManager(executor="host", reprobe_s=0.5),
                        min_device_batch=1,
                    ),
                    max_retries=0,
                ),
                cache_size=4096,
            )

        try:
            with Nemesis(
                4,
                home=str(tmp_path),
                node_factory=Nemesis.full_node_factory(),
                verifier_factory=factory,
            ) as net:
                net.wait_height(2, timeout=60)
                port = net.nodes[0].rpc_port
                code, body = _get_health(port)
                assert code == 200 and body["status"] == "ok", body
                assert body["checks"]["mesh"]["present"]

                fail.set_device_fault("shard2")  # one chip dies
                net.wait_progress(delta=1, timeout=60)
                body = _wait_status(port, "degraded", timeout=30)
                assert not body["checks"]["mesh"]["ok"], body
                assert (
                    body["checks"]["mesh"]["devices_active"]
                    < body["checks"]["mesh"]["devices_total"]
                )
                # a mesh shrink is BELOW the breaker: breakers stay green
                assert body["checks"]["breakers"]["ok"], body

                fail.clear_device_faults()  # re-probe restores the mesh
                net.wait_progress(delta=1, timeout=60)
                body = _wait_status(port, "ok", timeout=30)
                assert body["checks"]["mesh"]["ok"]
        finally:
            fail.clear_device_faults()


class TestHealthRoute:
    def test_post_json_rpc_health(self, tmp_path):
        """`health` is also a normal JSON-RPC method (the snapshot
        without HTTP-status semantics)."""
        from tendermint_tpu.testing.nemesis import Nemesis

        with Nemesis(
            2, home=str(tmp_path), node_factory=Nemesis.full_node_factory()
        ) as net:
            net.wait_height(2, timeout=60)
            out = _rpc(net.nodes[0].rpc_port, "health")
            assert out["status"] in ("ok", "degraded")
            assert "finality_slo" in out and "checks" in out
