"""Adversarial-input hardening at the p2p layer: misbehavior scoring,
bans, reader-thread resilience to malformed frames, and the bounded
per-peer claim tracking in the vote sets (ISSUE 9 satellites).
"""

from __future__ import annotations

import time

import pytest

from tendermint_tpu.p2p.connection import MAX_FRAME_SIZE, ChannelDescriptor, build_frame
from tendermint_tpu.p2p.peer import NodeInfo
from tendermint_tpu.p2p.score import MISBEHAVIOR_WEIGHTS, PeerScorer
from tendermint_tpu.p2p.switch import Reactor, Switch, connect_switches
from tendermint_tpu.p2p.transport import pipe_pair
from tendermint_tpu.telemetry import REGISTRY

CHAIN = "score-chain"


def wait_until(pred, timeout=10.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


class EchoReactor(Reactor):
    def __init__(self, chan=0x10):
        super().__init__()
        self.chan = chan
        self.received: list[bytes] = []

    def get_channels(self):
        return [ChannelDescriptor(self.chan)]

    def receive(self, chan_id, peer, payload):
        if payload == b"explode":
            raise ValueError("bad payload")
        self.received.append(payload)


def make_switch(n, reactor=None):
    sw = Switch(NodeInfo(node_id=f"peer{n}", moniker=f"p{n}", chain_id=CHAIN))
    sw.add_reactor("echo", reactor if reactor is not None else EchoReactor())
    sw.start()
    return sw


class TestPeerScorer:
    def test_accumulates_and_bans_at_threshold(self):
        clock = [0.0]
        s = PeerScorer(threshold=100, half_life_s=60, clock=lambda: clock[0])
        assert not s.debit("p", "bad_sig")  # 10
        for _ in range(8):
            s.debit("p", "bad_sig")
        assert not s.is_banned("p")
        assert s.debit("p", "bad_sig")  # crosses 100
        assert s.is_banned("p")

    def test_score_decays_with_half_life(self):
        clock = [0.0]
        s = PeerScorer(threshold=100, half_life_s=10, clock=lambda: clock[0])
        s.debit("p", "bad_frame")  # 25
        clock[0] = 10.0
        assert s.score("p") == pytest.approx(12.5)
        clock[0] = 1000.0
        assert s.score("p") < 0.01  # honest noise is forgiven

    def test_ban_expires(self):
        clock = [0.0]
        s = PeerScorer(ban_duration_s=30, clock=lambda: clock[0])
        s.ban("p")
        assert s.is_banned("p")
        clock[0] = 31.0
        assert not s.is_banned("p")

    def test_severe_kinds_ban_fast(self):
        s = PeerScorer(threshold=100)
        # a forged block cannot be produced honestly: one offense bans
        assert s.debit("liar", "forged_block")
        assert s.is_banned("liar")

    def test_weights_cover_the_registered_taxonomy(self):
        for kind in (
            "bad_frame",
            "oversize_frame",
            "bad_msg",
            "bad_sig",
            "bad_vote",
            "forged_block",
            "bad_evidence",
            "flood",
        ):
            assert MISBEHAVIOR_WEIGHTS[kind] > 0


class TestSwitchMisbehavior:
    def test_threshold_ban_disconnects_and_refuses_reconnect(self):
        a, b = make_switch(1), make_switch(2)
        try:
            connect_switches(a, b)
            assert a.n_peers() == 1
            for _ in range(20):
                a.report_misbehavior("peer2", "bad_sig")
            wait_until(lambda: a.n_peers() == 0, msg="banned peer dropped")
            assert a.scorer.is_banned("peer2")
            with pytest.raises(ValueError, match="banned"):
                connect_switches(a, b)
        finally:
            a.stop()
            b.stop()

    def test_reactor_exception_scores_and_drops_peer(self):
        bans_before = REGISTRY.counter_value(
            "tendermint_p2p_peer_misbehavior_total", kind="bad_msg"
        )
        a, b = make_switch(3), make_switch(4)
        try:
            connect_switches(a, b)
            pb = b.peers()[0]
            pb.try_send(0x10, b"explode")
            wait_until(lambda: a.n_peers() == 0, msg="offender dropped")
            assert (
                REGISTRY.counter_value(
                    "tendermint_p2p_peer_misbehavior_total", kind="bad_msg"
                )
                > bans_before
            )
            assert not a.scorer.is_banned("peer4")  # one offense != ban
        finally:
            a.stop()
            b.stop()


class TestReaderResilience:
    """Satellite regression: a malformed/truncated/oversized frame from
    a peer must disconnect THAT peer (debiting its score) — never crash
    or wedge the recv loop."""

    def _victim_with_raw_peer(self, reactor=None, node_id="raw-peer"):
        victim = make_switch(5, reactor)
        ea, eb = pipe_pair()
        victim.add_peer_endpoint(
            NodeInfo(node_id=node_id, moniker="raw", chain_id=CHAIN),
            ea,
            outbound=False,
        )
        return victim, eb

    def test_malformed_frame_drops_only_offender(self):
        reactor = EchoReactor()
        victim, raw = self._victim_with_raw_peer(reactor)
        honest = make_switch(6)
        before = REGISTRY.counter_value(
            "tendermint_p2p_peer_misbehavior_total", kind="bad_frame"
        )
        try:
            connect_switches(victim, honest)
            assert victim.n_peers() == 2
            # length-field lie: declares a huge payload that isn't there
            raw.send(b"\x10\xff\xff\xff\xff\x7f")
            wait_until(lambda: victim.n_peers() == 1, msg="offender dropped")
            assert (
                REGISTRY.counter_value(
                    "tendermint_p2p_peer_misbehavior_total", kind="bad_frame"
                )
                > before
            )
            # the switch (and the honest peer's reader) still works
            honest.peers()[0].try_send(0x10, b"still-alive")
            wait_until(
                lambda: b"still-alive" in reactor.received, msg="honest traffic flows"
            )
        finally:
            victim.stop()
            honest.stop()

    def test_oversize_frame_drops_peer(self):
        victim, raw = self._victim_with_raw_peer(node_id="raw-big")
        before = REGISTRY.counter_value(
            "tendermint_p2p_peer_misbehavior_total", kind="oversize_frame"
        )
        try:
            assert victim.n_peers() == 1
            raw.send(b"\x00" * (MAX_FRAME_SIZE + 1))
            wait_until(lambda: victim.n_peers() == 0, msg="oversize sender dropped")
            assert (
                REGISTRY.counter_value(
                    "tendermint_p2p_peer_misbehavior_total", kind="oversize_frame"
                )
                > before
            )
        finally:
            victim.stop()

    def test_repeat_bad_frame_offender_gets_banned(self):
        """Reconnect-and-garbage cycling is not free: frame offenses
        accumulate on the node id and end in a ban."""
        victim = make_switch(7)
        try:
            for i in range(6):
                ea, eb = pipe_pair()
                try:
                    victim.add_peer_endpoint(
                        NodeInfo(node_id="cycler", moniker="c", chain_id=CHAIN),
                        ea,
                        outbound=False,
                    )
                except ValueError:
                    break  # banned mid-cycle: exactly the point
                eb.send(b"\x10\xff\xff\xff\xff\x7f")
                wait_until(lambda: victim.n_peers() == 0, msg="dropped")
            assert victim.scorer.is_banned("cycler")
        finally:
            victim.stop()


class TestVoteSetClaimBounds:
    """Satellite regression: peer maj23 claims cannot grow unbounded
    per-round/per-height state."""

    def _vote_set(self):
        from tendermint_tpu.testing.nemesis import make_genesis
        from tendermint_tpu.state import make_genesis_state
        from tendermint_tpu.db.kv import MemDB
        from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE
        from tendermint_tpu.types.vote_set import VoteSet

        genesis, privs = make_genesis(4, chain_id=CHAIN)
        state = make_genesis_state(MemDB(), genesis)
        return (
            VoteSet(CHAIN, 1, 0, VOTE_TYPE_PREVOTE, state.validators),
            state.validators,
        )

    def test_claim_created_tallies_are_capped(self):
        from tendermint_tpu.types.block_id import BlockID
        from tendermint_tpu.types.part_set import PartSetHeader
        from tendermint_tpu.types.vote_set import VoteSet

        vs, _vals = self._vote_set()
        for i in range(200):
            vs.set_peer_maj23(
                f"flooder{i}",
                BlockID(i.to_bytes(20, "big"), PartSetHeader.zero()),
            )
        # empty claim-tallies evicted past the cap (+1 for the newest)
        assert len(vs.votes_by_block) <= VoteSet.MAX_PEER_CLAIMS + 1

    def test_height_vote_set_refuses_round_claim_flood(self):
        from tendermint_tpu.consensus.round_state import HeightVoteSet
        from tendermint_tpu.types.block_id import BlockID
        from tendermint_tpu.types.part_set import PartSetHeader
        from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE

        _, vals = self._vote_set()
        hvs = HeightVoteSet(CHAIN, 1, vals)
        bid = BlockID(b"\x01" * 20, PartSetHeader.zero())
        for r in range(2, 500):
            hvs.set_peer_maj23(r, VOTE_TYPE_PREVOTE, "flooder", bid)
        # 1 base round pair + round 1 (catchup window) + 2 per-peer
        # catchup rounds: far below the 500 a flood asked for
        assert len(hvs._round_vote_sets) <= 6
