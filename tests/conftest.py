"""Test environment: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding paths compile and run without TPU hardware (the driver separately
dry-runs multi-chip via __graft_entry__.dryrun_multichip)."""

import os

# Must be set before jax is imported anywhere.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
