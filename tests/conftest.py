"""Test environment: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding paths compile and run without TPU hardware (the driver separately
dry-runs multi-chip via __graft_entry__.dryrun_multichip).

Note: this machine's axon sitecustomize registers the TPU plugin and
overwrites `jax_platforms` — the env var alone is not enough, so we also
update the config after importing jax (before any backend initialization).

This file is also the tier-1 wiring for tmlint (tendermint_tpu/analysis/):
the three original collection lints are thin shims over the engine's rules
(M001 metric catalog, M002 span catalog, M003 kernel marks), the FULL rule
set gates collection on the package + tools/, and the runtime lock-rank
sanitizer (utils/lockrank.py) is enabled for the whole run — any rank
inversion or lock-order cycle a test provokes fails that test with the
acquisition-stack report.
"""

import os

# Must be set before jax initializes a backend.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Lock-rank sanitizer on for the whole suite (before any tendermint_tpu
# import constructs a lock). TENDERMINT_TPU_LOCKRANK=0 opts out locally.
os.environ.setdefault("TENDERMINT_TPU_LOCKRANK", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pathlib  # noqa: E402

import pytest  # noqa: E402

_REPO = pathlib.Path(__file__).resolve().parents[1]


def lint_kernel_marks(items) -> list[str]:
    """Marker lint shim: every `kernel`-marked test must ALSO be `slow`
    (tier-1 `-m 'not slow'` overrides pytest.ini's `-m 'not kernel'`;
    see the ROADMAP tier-1 note). Logic lives in tmlint rule M003."""
    from tendermint_tpu.analysis.rules_catalog import kernel_mark_offenders

    return kernel_mark_offenders(items)


def lint_metric_catalog(roots=None) -> list[str]:
    """Catalog lint shim (tmlint M001): every `tendermint_*` metric
    literal in the package (and tools/) must be registered by
    `telemetry/metrics.py`. Returns `path:name` offenders."""
    from tendermint_tpu.analysis.rules_catalog import metric_offenders

    return metric_offenders(roots)


def lint_span_catalog(roots=None) -> list[str]:
    """Span-name lint shim (tmlint M002): every literal passed to
    `TRACER.span("…")` / `TRACER.add("…", …)` must be in
    `telemetry/metrics.py`'s SPAN_CATALOG. Returns `path:name`
    offenders."""
    from tendermint_tpu.analysis.rules_catalog import span_offenders

    return span_offenders(roots)


def run_tmlint_gate() -> str | None:
    """Full tmlint pass over the package + tools with the repo baseline;
    returns the rendered report when it fails, None when clean. Gates
    tier-1 collection so concurrency/wire/purity invariants cannot
    regress silently (<2 s on the whole tree)."""
    from tendermint_tpu.analysis import engine

    report = engine.lint_paths(
        [_REPO / "tendermint_tpu", _REPO / "tools"],
        baseline_path=_REPO / "tools" / "tmlint_baseline.json",
        root=_REPO,
    )
    if report.ok:
        return None
    return engine.render_report(report)


def pytest_collection_modifyitems(config, items):
    bad = lint_kernel_marks(items)
    if bad:
        raise pytest.UsageError(
            "kernel-marked tests missing the slow mark (tier-1 `-m 'not "
            "slow'` would compile their XLA:CPU kernels): "
            + ", ".join(sorted(bad)[:10])
        )
    bad_metrics = lint_metric_catalog()
    if bad_metrics:
        raise pytest.UsageError(
            "tendermint_* metric names used in code but missing from "
            "telemetry/metrics.py's catalog: " + ", ".join(bad_metrics[:10])
        )
    bad_spans = lint_span_catalog()
    if bad_spans:
        raise pytest.UsageError(
            "span names recorded in code but missing from "
            "telemetry/metrics.py's SPAN_CATALOG: " + ", ".join(bad_spans[:10])
        )
    tmlint_failure = run_tmlint_gate()
    if tmlint_failure is not None:
        raise pytest.UsageError(
            "tmlint found repo-invariant violations (run `python -m "
            "tools.tmlint` locally; suppress false positives with a "
            "reasoned `# tmlint: disable=RULE -- why`):\n" + tmlint_failure
        )


@pytest.fixture(autouse=True)
def _lockrank_guard():
    """Turn lock-rank violations into failures of the test that
    provoked them, carrying both threads' acquisition stacks. Violations
    recorded by background threads between tests surface on the next
    test — still loud, occasionally mis-attributed by one test."""
    yield
    from tendermint_tpu.utils import lockrank

    violations = lockrank.drain()
    if violations:
        pytest.fail(
            "lock-rank sanitizer recorded violation(s) during this test "
            "(utils/lockrank.py):\n" + lockrank_render(violations),
            pytrace=False,
        )


def lockrank_render(violations) -> str:
    from tendermint_tpu.utils import lockrank

    return "\n".join(lockrank.render_violation(v) for v in violations)
