"""Test environment: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding paths compile and run without TPU hardware (the driver separately
dry-runs multi-chip via __graft_entry__.dryrun_multichip).

Note: this machine's axon sitecustomize registers the TPU plugin and
overwrites `jax_platforms` — the env var alone is not enough, so we also
update the config after importing jax (before any backend initialization).
"""

import os

# Must be set before jax initializes a backend.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def lint_kernel_marks(items) -> list[str]:
    """Marker lint: every `kernel`-marked test must ALSO be `slow`.

    Tier-1 selects `-m 'not slow'`, which OVERRIDES pytest.ini's
    `-m 'not kernel'` — a kernel-only mark would pull ~20 min of XLA:CPU
    kernel compiles into the fast lane and time the whole run out
    (ROADMAP tier-1 note). Returns offending node ids."""
    return [
        item.nodeid
        for item in items
        if item.get_closest_marker("kernel") is not None
        and item.get_closest_marker("slow") is None
    ]


def lint_metric_catalog(roots=None) -> list[str]:
    """Catalog lint: every `tendermint_*` metric name used as a string
    literal in the package (and tools/) must be registered by
    `telemetry/metrics.py` — an unregistered name means a dashboard or
    invariant is querying a series that will never exist. Returns
    `path:name` offenders. Histogram exposition suffixes
    (`_bucket`/`_sum`/`_count`) resolve to their base family."""
    import pathlib
    import re

    import tendermint_tpu.telemetry.metrics  # noqa: F401 — fills the registry
    from tendermint_tpu.telemetry import REGISTRY

    repo = pathlib.Path(__file__).resolve().parents[1]
    if roots is None:
        roots = [repo / "tendermint_tpu", repo / "tools"]
    registered = {m.name for m in REGISTRY.metrics()}
    pat = re.compile(r"""["'](tendermint_[a-z0-9_]+)["']""")
    offenders: list[str] = []
    for root in roots:
        for path in sorted(pathlib.Path(root).rglob("*.py")):
            for name in pat.findall(path.read_text(encoding="utf-8")):
                if name.startswith("tendermint_tpu"):
                    continue  # the package name, not a metric
                base = re.sub(r"_(bucket|sum|count)$", "", name)
                if name in registered or base in registered:
                    continue
                try:
                    shown = path.relative_to(repo)
                except ValueError:  # lint tests point at tmp dirs
                    shown = path
                offenders.append(f"{shown}:{name}")
    return offenders


def lint_span_catalog(roots=None) -> list[str]:
    """Span-name lint: every literal name passed to `TRACER.span("…")`
    or `TRACER.add("…", …)` in the package (and tools/) must be
    registered in `telemetry/metrics.py`'s SPAN_CATALOG — same
    discipline as the metric lint: an uncataloged span name means a
    timeline/dashboard query that silently matches nothing. Returns
    `path:name` offenders."""
    import pathlib
    import re

    from tendermint_tpu.telemetry.metrics import SPAN_CATALOG

    repo = pathlib.Path(__file__).resolve().parents[1]
    if roots is None:
        roots = [repo / "tendermint_tpu", repo / "tools"]
    pat = re.compile(r"""TRACER\.(?:span|add)\(\s*["']([a-z0-9_.]+)["']""")
    offenders: list[str] = []
    for root in roots:
        for path in sorted(pathlib.Path(root).rglob("*.py")):
            for name in pat.findall(path.read_text(encoding="utf-8")):
                if name in SPAN_CATALOG:
                    continue
                try:
                    shown = path.relative_to(repo)
                except ValueError:  # lint tests point at tmp dirs
                    shown = path
                offenders.append(f"{shown}:{name}")
    return offenders


def pytest_collection_modifyitems(config, items):
    bad = lint_kernel_marks(items)
    if bad:
        raise pytest.UsageError(
            "kernel-marked tests missing the slow mark (tier-1 `-m 'not "
            "slow'` would compile their XLA:CPU kernels): "
            + ", ".join(sorted(bad)[:10])
        )
    bad_metrics = lint_metric_catalog()
    if bad_metrics:
        raise pytest.UsageError(
            "tendermint_* metric names used in code but missing from "
            "telemetry/metrics.py's catalog: " + ", ".join(bad_metrics[:10])
        )
    bad_spans = lint_span_catalog()
    if bad_spans:
        raise pytest.UsageError(
            "span names recorded in code but missing from "
            "telemetry/metrics.py's SPAN_CATALOG: " + ", ".join(bad_spans[:10])
        )
