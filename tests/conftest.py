"""Test environment: force JAX onto a virtual 8-device CPU mesh so multi-chip
sharding paths compile and run without TPU hardware (the driver separately
dry-runs multi-chip via __graft_entry__.dryrun_multichip).

Note: this machine's axon sitecustomize registers the TPU plugin and
overwrites `jax_platforms` — the env var alone is not enough, so we also
update the config after importing jax (before any backend initialization).
"""

import os

# Must be set before jax initializes a backend.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
