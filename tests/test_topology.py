"""WAN link model: delivery wheel, jitter, token-bucket bandwidth,
topology shaping — and the golden no-op contract for all-zero knobs.

The thread-count regression here is the PR's satellite guarantee: the
delayed-delivery path holds steady-state thread count O(1) per
process (one wheel thread), not O(in-flight sends) — the old
one-`threading.Timer`-per-send shape at WAN delays meant thousands of
short-lived threads.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

from tendermint_tpu.p2p.transport import (
    _WHEEL,
    ChaosEndpoint,
    FuzzConfig,
    FuzzedEndpoint,
    LinkChaos,
    _TokenBucket,
    pipe_pair,
)
from tendermint_tpu.testing.topology import (
    DEFAULT_RTT_MS,
    LinkProfile,
    WanTopology,
    slow_validator_topology,
    uniform_topology,
)


def _drain(ep, n: int, timeout: float = 5.0) -> list[bytes]:
    out = []
    deadline = time.monotonic() + timeout
    while len(out) < n and time.monotonic() < deadline:
        try:
            out.append(ep.recv(timeout=0.2))
        except Exception:
            pass
    return out


class TestGoldenNoop:
    """All-zero chaos/fuzz knobs must be byte-for-byte pass-through:
    no RNG draws, no wheel rides, in-order synchronous delivery."""

    def test_zero_linkchaos_is_passthrough(self):
        a, b = pipe_pair()
        chaos = LinkChaos(seed=3)
        ep = ChaosEndpoint(a, chaos)
        state_before = chaos._rng.getstate()
        pending_before = _WHEEL.pending()
        msgs = [b"m%d" % i for i in range(50)]
        for m in msgs:
            assert ep.send(m)
        assert _drain(b, 50) == msgs  # synchronous, in order
        assert chaos._rng.getstate() == state_before  # zero RNG draws
        assert _WHEEL.pending() == pending_before  # nothing scheduled

    def test_zero_fuzzconfig_draw_sequence_unchanged(self):
        """The grown FuzzConfig fields (jitter_s, bandwidth_bps) must
        not consume RNG draws when zero: a seeded fuzzed link's
        drop/dup pattern is exactly what the pre-WAN draw order
        produces (mirrored here draw-for-draw)."""
        cfg = FuzzConfig(prob_drop_rw=0.3, prob_dup=0.3, seed=42)
        a, b = pipe_pair()
        ep = FuzzedEndpoint(a, cfg)
        msgs = [b"g%d" % i for i in range(40)]
        for m in msgs:
            ep.send(m)
        got = _drain(b, 80, timeout=1.0)

        rng = random.Random(42)  # the documented draw order, replayed
        expect: list[bytes] = []
        for m in msgs:
            if rng.random() < 0.3:  # prob_drop_rw
                continue
            if rng.random() < 0.3:  # prob_dup
                expect.append(m)
            expect.append(m)
        assert got == expect


class TestDeliveryWheel:
    def test_delay_holds_thread_count_flat(self):
        """Soak: hundreds of in-flight delayed sends, O(1) threads."""
        a, b = pipe_pair()
        chaos = LinkChaos(seed=1)
        chaos.delay_s = 0.25
        ep = ChaosEndpoint(a, chaos)
        base = threading.active_count()
        for i in range(400):
            ep.send(b"soak%d" % i)
        in_flight = _WHEEL.pending()
        assert in_flight >= 300, f"expected a deep wheel, got {in_flight}"
        # one wheel thread, plus scheduler noise headroom — NOT O(400)
        assert threading.active_count() <= base + 2, (
            f"thread count grew from {base} to {threading.active_count()} "
            f"with {in_flight} delayed sends in flight"
        )
        got = _drain(b, 400, timeout=5.0)
        assert len(got) == 400
        assert threading.active_count() <= base + 2

    def test_fixed_delay_preserves_order(self):
        a, b = pipe_pair()
        chaos = LinkChaos(seed=1)
        chaos.delay_s = 0.03
        ep = ChaosEndpoint(a, chaos)
        msgs = [b"o%d" % i for i in range(30)]
        t0 = time.monotonic()
        for m in msgs:
            ep.send(m)
        got = _drain(b, 30)
        assert got == msgs  # fixed latency == FIFO pipe
        assert time.monotonic() - t0 >= 0.03  # the delay actually happened

    def test_jitter_reorders(self):
        a, b = pipe_pair()
        chaos = LinkChaos(seed=7)
        chaos.delay_s = 0.01
        chaos.jitter_s = 0.08
        ep = ChaosEndpoint(a, chaos)
        msgs = [b"j%02d" % i for i in range(40)]
        for m in msgs:
            ep.send(m)
        got = _drain(b, 40)
        assert sorted(got) == msgs  # nothing lost
        assert got != msgs  # ...but the path reordered

    def test_partition_started_mid_flight_drops_delivery(self):
        a, b = pipe_pair()
        chaos = LinkChaos(seed=1)
        chaos.delay_s = 0.15
        ep = ChaosEndpoint(a, chaos)
        ep.send(b"doomed")
        chaos.partitioned = True  # partition lands while in flight
        assert _drain(b, 1, timeout=0.5) == []

    def test_closed_endpoint_does_not_kill_wheel(self):
        a, b = pipe_pair()
        chaos = LinkChaos(seed=1)
        chaos.delay_s = 0.05
        ep = ChaosEndpoint(a, chaos)
        ep.send(b"into-the-void")
        a.close()
        b.close()
        time.sleep(0.15)  # delivery fires into the closed endpoint
        # wheel must still deliver for OTHER links afterwards
        c, d = pipe_pair()
        chaos2 = LinkChaos(seed=2)
        chaos2.delay_s = 0.02
        ep2 = ChaosEndpoint(c, chaos2)
        ep2.send(b"alive")
        assert _drain(d, 1) == [b"alive"]


class TestTokenBucket:
    def test_serialization_times(self):
        bucket = _TokenBucket()
        # 8000 bps = 1000 bytes/s; no burst: each 100B costs 0.1s
        assert bucket.wait(100, now=50.0, bps=8000.0, burst_bytes=0) == pytest.approx(0.1)
        assert bucket.wait(100, now=50.0, bps=8000.0, burst_bytes=0) == pytest.approx(0.2)
        # idle time refunds the queue
        assert bucket.wait(100, now=60.0, bps=8000.0, burst_bytes=0) == pytest.approx(0.1)

    def test_burst_credit_absorbs_spikes(self):
        bucket = _TokenBucket()
        # 1000 bytes/s with a 1000-byte burst: the first 1000B are free
        waits = [
            bucket.wait(100, now=10.0, bps=8000.0, burst_bytes=1000)
            for _ in range(10)
        ]
        assert all(w == 0.0 for w in waits)
        assert bucket.wait(100, now=10.0, bps=8000.0, burst_bytes=1000) > 0.0

    def test_zero_bps_uncapped(self):
        bucket = _TokenBucket()
        assert bucket.wait(10**9, now=1.0, bps=0.0, burst_bytes=0) == 0.0

    def test_chaos_bandwidth_cap_delays_delivery(self):
        a, b = pipe_pair()
        chaos = LinkChaos(seed=1)
        chaos.bandwidth_bps = 80_000.0  # 10 KB/s
        chaos.bandwidth_burst_bytes = 0
        ep = ChaosEndpoint(a, chaos)
        t0 = time.monotonic()
        for i in range(5):
            ep.send(b"x" * 1000)  # 5 KB over a 10 KB/s pipe ≈ 0.5s
        got = _drain(b, 5)
        assert len(got) == 5
        assert time.monotonic() - t0 >= 0.35  # serialized, sender unblocked


class TestWanTopology:
    def test_default_matrix_symmetric_and_complete(self):
        regions = ("us-east", "us-west", "eu-west", "ap-northeast", "sa-east")
        for a in regions:
            for b in regions:
                assert DEFAULT_RTT_MS[(a, b)] == DEFAULT_RTT_MS[(b, a)]
                if a != b:
                    assert DEFAULT_RTT_MS[(a, b)] > 10.0

    def test_shape_writes_linkchaos_knobs(self):
        topo = WanTopology(placement=["us-east", "eu-west"], bandwidth_mbps=10.0)
        chaos = LinkChaos(seed=1)
        topo.shape(chaos, 0, 1)
        rtt = DEFAULT_RTT_MS[("us-east", "eu-west")]
        assert chaos.delay_s == pytest.approx(rtt / 2 / 1000)
        assert chaos.jitter_s == pytest.approx(rtt * 0.10 / 1000)
        assert chaos.bandwidth_bps == pytest.approx(10e6)

    def test_intra_region_stays_fast_and_uncapped(self):
        topo = WanTopology(
            placement=["us-east", "us-east"], bandwidth_mbps=10.0, loss=0.05
        )
        p = topo.profile(0, 1)
        assert p.rtt_ms <= 2.0
        assert p.bandwidth_mbps == 0.0
        assert p.loss == 0.0

    def test_asymmetric_override(self):
        topo = uniform_topology(rtt_ms=20.0)
        topo.overrides[(0, 1)] = LinkProfile(rtt_ms=300.0)
        assert topo.profile(0, 1).rtt_ms == 300.0
        assert topo.profile(1, 0).rtt_ms == 20.0  # reverse untouched

    def test_scale_multiplies_delays(self):
        topo = uniform_topology(rtt_ms=100.0, scale=0.1)
        chaos = LinkChaos(seed=1)
        topo.shape(chaos, 0, 1)
        assert chaos.delay_s == pytest.approx(0.005)

    def test_partition_groups_cut_one_region(self):
        topo = WanTopology(placement=["us-east", "us-east", "eu-west", "ap-northeast"])
        groups = topo.partition_groups(4, "us-east")
        assert groups == [{0, 1}, {2, 3}]
        with pytest.raises(ValueError):
            topo.partition_groups(4, "sa-east")

    def test_placement_wraps_round_robin(self):
        topo = WanTopology(placement=["us-east", "eu-west"])
        assert topo.region_of(0) == topo.region_of(2) == "us-east"
        assert topo.region_of(1) == topo.region_of(3) == "eu-west"

    def test_dict_round_trip(self):
        topo = slow_validator_topology(
            slow=2, base_rtt_ms=30.0, slow_rtt_ms=250.0, n_nodes=4, scale=0.2
        )
        clone = WanTopology.from_dict(topo.to_dict())
        for i in range(4):
            for j in range(4):
                if i != j:
                    assert clone.profile(i, j) == topo.profile(i, j)
        assert clone.scale == topo.scale
