"""Scenario engine: schema validation, deterministic churn rotation,
and the tier-1 end-to-end scenarios.

`test_churn_small_end_to_end` is the PR's validator-churn acceptance
test: ≥25% of the active window rotates every K heights through ≥3
full epochs, and BOTH rotation seams are asserted — PR 14's
speculated-round rebuild (`pipeline_stats["valset_rebuilds"]`) and
PR 15's bisection bridging from the genesis valset across every
epoch boundary — with the Nemesis no-fork/commit-agreement invariants
green throughout. The heavy library entries (flash crowd, regional
outage, churn storm, partition-during-churn) run slow-marked and in
`tools/bench_hotpath.py --section scenario_finality`.
"""

from __future__ import annotations

import pytest

from tendermint_tpu.testing.scenario import (
    SCENARIO_LIBRARY,
    ChurnApp,
    ScenarioRunner,
    churn_app_factory,
    run_library,
    validate_scenario,
)


class TestSchema:
    def test_defaults_fill_in(self):
        spec = validate_scenario({"name": "x"})
        assert spec["nodes"] == 4
        assert spec["kind"] == "core"
        assert spec["run"]["target_height"] == 20

    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown scenario keys"):
            validate_scenario({"name": "x", "topologee": {}})

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError, match="unknown timeline action"):
            validate_scenario(
                {"name": "x", "timeline": [{"at_height": 1, "action": "explode"}]}
            )

    def test_timeline_event_needs_a_trigger(self):
        with pytest.raises(ValueError, match="at_height or at_s"):
            validate_scenario({"name": "x", "timeline": [{"action": "heal"}]})

    def test_churn_requires_active_window(self):
        with pytest.raises(ValueError, match="n_active"):
            validate_scenario({"name": "x", "churn": {"every": 4, "shift": 1}})

    def test_load_requires_full_nodes(self):
        with pytest.raises(ValueError, match="kind=full"):
            validate_scenario({"name": "x", "load": {"rate": 10}})

    def test_library_specs_all_validate(self):
        for name, spec in SCENARIO_LIBRARY.items():
            validated = validate_scenario(spec)
            assert validated["name"] == name


class TestChurnApp:
    def _pool(self, n=6):
        return [bytes([i]) * 32 for i in range(n)]

    def test_no_rotation_off_boundary(self):
        app = ChurnApp(self._pool(), active=4, every=4, shift=1)
        for h in (1, 2, 3, 5, 7, 9):
            assert app.end_block(h) == []

    def test_rotation_diff_is_25_percent(self):
        pool = self._pool()
        app = ChurnApp(pool, active=4, every=4, shift=1)
        changes = app.end_block(4)  # epoch 0 {0,1,2,3} -> epoch 1 {1,2,3,4}
        assert [(c.pub_key, c.power) for c in changes] == [
            (pool[0], 0),  # removed
            (pool[4], 10),  # admitted
        ]

    def test_window_wraps_the_pool(self):
        pool = self._pool()
        app = ChurnApp(pool, active=4, every=4, shift=1)
        changes = app.end_block(12)  # epoch 3 {3,4,5,0}: wraps to index 0
        assert (pool[0], 10) in [(c.pub_key, c.power) for c in changes]

    def test_two_apps_agree(self):
        """Rotation is a pure function of height — the determinism
        consensus needs from every replica's EndBlock."""
        a = ChurnApp(self._pool(), active=4, every=3, shift=2)
        b = ChurnApp(self._pool(), active=4, every=3, shift=2)
        for h in range(1, 20):
            assert [(c.pub_key, c.power) for c in a.end_block(h)] == [
                (c.pub_key, c.power) for c in b.end_block(h)
            ]

    def test_factory_pool_matches_genesis(self):
        from tendermint_tpu.testing.nemesis import make_genesis

        factory = churn_app_factory(6, "c", active=4, every=4, shift=1)
        app = factory()
        _, privs = make_genesis(6, chain_id="c", n_active=4)
        changes = app.end_block(4)
        admitted = {c.pub_key for c in changes if c.power > 0}
        assert admitted == {privs[4].pub_key.data}


class TestEndToEnd:
    def test_churn_small_end_to_end(self, tmp_path):
        """≥25% window rotation every 4 heights, ≥3 full epochs:
        speculation rebuilds fire at every boundary, the light client
        bisects genesis→tip across all rotations, no fork."""
        report = ScenarioRunner(home=str(tmp_path)).run(
            SCENARIO_LIBRARY["churn_small"]
        )
        assert report["ok"], report["failures"]
        assert report["epochs"] >= 3
        assert report["valset_rebuilds"] >= 3  # PR 14 seam exercised
        assert report["bisection"]["verified_to"] >= 16  # PR 15 seam exercised
        assert min(report["heights"]) >= 16

    def test_slow_wan_validator_end_to_end(self, tmp_path):
        """Adaptive timeouts learn the slow path: derived propose
        timeout converges above the injected one-way delay and round
        skips stop once warmed."""
        report = ScenarioRunner(home=str(tmp_path)).run(
            SCENARIO_LIBRARY["slow_wan_validator"]
        )
        assert report["ok"], report["failures"]
        assert (
            report["propose_timeout_s"]["min"] > report["max_one_way_delay_s"]
        )
        assert report["round_skips_post_warm"] == 0


@pytest.mark.slow
class TestLibraryHeavy:
    @pytest.mark.parametrize(
        "name",
        ["regional_outage", "churn_storm", "partition_during_churn", "flash_crowd"],
    )
    def test_library_scenario(self, name, tmp_path):
        report = ScenarioRunner(home=str(tmp_path)).run(SCENARIO_LIBRARY[name])
        assert report["ok"], (name, report["failures"])

    def test_run_library_filters(self, tmp_path):
        reports = run_library(names=["churn_small"], home=str(tmp_path))
        assert [r["scenario"] for r in reports] == ["churn_small"]
