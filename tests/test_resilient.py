"""Fault-tolerant device dispatch: circuit breaker + resilient services.

The degradation contract under test (services/resilient.py): a sick
device backend must cost at most `threshold` failed dispatches before
every caller transparently runs on the host fallback; a recovered
device must be re-adopted after one successful probe; verdicts/roots
must be correct in every state.
"""

from __future__ import annotations

import numpy as np
import pytest

from tendermint_tpu.services.hasher import TreeHasher
from tendermint_tpu.services.resilient import (
    ResilientTreeHasher,
    ResilientVerifier,
)
from tendermint_tpu.services.verifier import BatchVerifier, HostBatchVerifier
from tendermint_tpu.utils import fail
from tendermint_tpu.utils.circuit import CLOSED, HALF_OPEN, OPEN, CircuitBreaker

from tests.helpers import det_priv_keys


@pytest.fixture(autouse=True)
def _clean_faults():
    fail.clear_device_faults()
    yield
    fail.clear_device_faults()


def _triples(n, corrupt=()):
    keys = det_priv_keys(n)
    out = []
    for i, k in enumerate(keys):
        msg = bytes([i]) * 8
        sig = k.sign(msg)
        if i in corrupt:
            sig = sig[:10] + bytes([sig[10] ^ 0xFF]) + sig[11:]
        out.append((k.pub_key.data, msg, sig))
    return out


class _FlakyVerifier(BatchVerifier):
    """Programmable primary: fails while `broken`, else verifies on host."""

    def __init__(self):
        super().__init__()
        self.broken = False
        self.calls = 0
        self._host = HostBatchVerifier()

    def verify_batch(self, triples):
        self.calls += 1
        if self.broken:
            raise RuntimeError("device exploded")
        return self._host.verify_batch(triples)


class TestCircuitBreaker:
    def test_trips_after_threshold_consecutive_failures(self):
        clock = [0.0]
        br = CircuitBreaker(failure_threshold=3, reset_timeout_s=5.0, clock=lambda: clock[0])
        assert br.state == CLOSED
        for _ in range(2):
            br.record_failure()
        assert br.state == CLOSED  # 2 < threshold
        br.record_failure()
        assert br.state == OPEN
        assert not br.allow()

    def test_success_resets_failure_streak(self):
        br = CircuitBreaker(failure_threshold=2)
        br.record_failure()
        br.record_success()
        br.record_failure()
        assert br.state == CLOSED  # never 2 consecutive

    def test_half_open_admits_one_probe(self):
        clock = [0.0]
        br = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0, clock=lambda: clock[0])
        br.record_failure()
        assert br.state == OPEN and not br.allow()
        clock[0] = 5.1
        assert br.state == HALF_OPEN
        assert br.allow()  # the probe
        assert not br.allow()  # concurrent caller blocked while probe in flight
        br.record_success()
        assert br.state == CLOSED and br.allow()

    def test_failed_probe_reopens_for_full_window(self):
        clock = [0.0]
        br = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0, clock=lambda: clock[0])
        br.record_failure()
        clock[0] = 5.1
        assert br.allow()
        br.record_failure()  # probe failed
        assert br.state == OPEN
        clock[0] = 10.0  # 4.9s after reopen: still open
        assert not br.allow()
        clock[0] = 10.3
        assert br.allow()

    def test_state_change_callback_and_snapshot(self):
        transitions = []
        br = CircuitBreaker(
            failure_threshold=1,
            reset_timeout_s=0.0,
            on_state_change=lambda old, new: transitions.append((old, new)),
        )
        br.record_failure()
        br.allow()
        br.record_success()
        assert (CLOSED, OPEN) in transitions
        assert transitions[-1][1] == CLOSED
        snap = br.snapshot()
        assert snap["times_opened"] == 1
        assert snap["total_failures"] == 1


class TestResilientVerifier:
    def _rv(self, primary, threshold=2, reset_s=0.05):
        return ResilientVerifier(
            primary,
            breaker=CircuitBreaker(failure_threshold=threshold, reset_timeout_s=reset_s),
            max_retries=0,
        )

    def test_verdicts_correct_in_every_state(self):
        primary = _FlakyVerifier()
        rv = self._rv(primary)
        triples = _triples(4, corrupt=(2,))
        expect = [True, True, False, True]

        assert list(rv.verify_batch(triples)) == expect  # healthy
        primary.broken = True
        assert list(rv.verify_batch(triples)) == expect  # fallback, breaker counting
        assert list(rv.verify_batch(triples)) == expect
        assert rv.breaker.state == OPEN
        assert rv.degraded
        calls_when_open = primary.calls
        assert list(rv.verify_batch(triples)) == expect  # open: primary not touched
        assert primary.calls == calls_when_open

    def test_breaker_recloses_after_recovery(self):
        import time

        primary = _FlakyVerifier()
        rv = self._rv(primary)
        triples = _triples(2)
        primary.broken = True
        rv.verify_batch(triples)
        rv.verify_batch(triples)
        assert rv.breaker.state == OPEN
        primary.broken = False
        time.sleep(0.06)  # reset window elapses -> half-open probe
        assert list(rv.verify_batch(triples)) == [True, True]
        assert rv.breaker.state == CLOSED
        assert not rv.degraded

    def test_env_fault_injection_counts_down(self):
        primary = _FlakyVerifier()
        rv = self._rv(primary, threshold=5)
        fail.set_device_fault("verify", count=2)
        triples = _triples(2)
        before = primary.calls
        rv.verify_batch(triples)  # injected fault -> fallback
        rv.verify_batch(triples)  # injected fault -> fallback
        assert primary.calls == before  # primary never reached
        assert list(rv.verify_batch(triples)) == [True, True]  # budget spent
        assert primary.calls == before + 1

    def test_verify_commits_host_fallback_shape(self):
        primary = _FlakyVerifier()  # no verify_commits attribute
        rv = self._rv(primary)
        keys = det_priv_keys(3)
        pubs = [k.pub_key.data for k in keys]
        msgs = [bytes([i]) for i in range(3)]
        sigs = [k.sign(m) for k, m in zip(keys, msgs)]
        commits = [
            (msgs, sigs),
            ([msgs[0], None, msgs[2]], [sigs[0], None, sigs[2]]),
        ]
        grid = rv.verify_commits(pubs, commits)
        assert grid.shape == (2, 3)
        assert grid[0].tolist() == [True, True, True]
        assert grid[1].tolist() == [True, False, True]

    def test_dispatch_timeout_counts_as_failure(self):
        class Hanging(BatchVerifier):
            def verify_batch(self, triples):
                import time

                time.sleep(5)
                return np.ones(len(triples), dtype=bool)

        rv = ResilientVerifier(
            Hanging(),
            breaker=CircuitBreaker(failure_threshold=1, reset_timeout_s=60),
            max_retries=0,
            dispatch_timeout_s=0.1,
        )
        triples = _triples(1)
        assert list(rv.verify_batch(triples)) == [True]  # host answered
        assert rv.breaker.state == OPEN


class TestResilientTreeHasher:
    class _FlakyHasher(TreeHasher):
        def __init__(self):
            super().__init__(backend="host")
            self.broken = False

        def root_from_items(self, items):
            if self.broken:
                raise RuntimeError("device tree exploded")
            return super().root_from_items(items)

        def root_from_hashes(self, hashes):
            if self.broken:
                raise RuntimeError("device tree exploded")
            return super().root_from_hashes(hashes)

    def test_roots_identical_across_degradation(self):
        primary = self._FlakyHasher()
        rh = ResilientTreeHasher(
            primary,
            breaker=CircuitBreaker(failure_threshold=1, reset_timeout_s=60),
            max_retries=0,
        )
        items = [bytes([i]) * 10 for i in range(7)]
        healthy = rh.root_from_items(items)
        primary.broken = True
        degraded = rh.root_from_items(items)
        assert healthy == degraded
        assert rh.breaker.state == OPEN
        host = TreeHasher(backend="host")
        assert degraded == host.root_from_items(items)

    def test_hash_fault_injection_env_spec(self):
        rh = ResilientTreeHasher(
            self._FlakyHasher(),
            breaker=CircuitBreaker(failure_threshold=1, reset_timeout_s=60),
            max_retries=0,
        )
        fail.set_device_fault("hash")
        items = [b"a", b"b", b"c"]
        assert rh.root_from_items(items) == TreeHasher(backend="host").root_from_items(items)
        assert rh.breaker.state == OPEN


class TestFaultSpecParsing:
    def test_env_spec_kinds_and_budgets(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_TPU_DEVICE_FAIL", "verify:1,hash")
        fail.clear_device_faults()
        monkeypatch.setattr(fail, "_device_faults", None)
        with pytest.raises(fail.InjectedDeviceFault):
            fail.device_fail_point("verify")
        fail.device_fail_point("verify")  # budget of 1 spent: no raise
        with pytest.raises(fail.InjectedDeviceFault):
            fail.device_fail_point("hash")  # unbounded
        with pytest.raises(fail.InjectedDeviceFault):
            fail.device_fail_point("hash")

    def test_all_kind_hits_everything(self):
        fail.set_device_fault("all")
        for kind in ("verify", "hash"):
            with pytest.raises(fail.InjectedDeviceFault):
                fail.device_fail_point(kind)
        fail.clear_device_faults()
        fail.device_fail_point("verify")  # cleared: silent

    def test_default_factories_wrap_when_armed(self, monkeypatch):
        from tendermint_tpu.services import hasher as hasher_mod
        from tendermint_tpu.services import verifier as verifier_mod

        from tendermint_tpu.services.batcher import CoalescingVerifier

        fail.set_device_fault("verify")
        monkeypatch.setattr(verifier_mod, "_DEFAULT", None)
        v = verifier_mod.default_verifier()
        # the coalescing facade is always outermost; the resilient wrap
        # appears underneath it when faults are armed
        assert isinstance(v, CoalescingVerifier)
        assert isinstance(v.inner, ResilientVerifier)
        h = hasher_mod.auto_hasher()
        assert isinstance(h, ResilientTreeHasher)
        monkeypatch.setattr(verifier_mod, "_DEFAULT", None)
        fail.clear_device_faults()
        v2 = verifier_mod.default_verifier()
        assert isinstance(v2, CoalescingVerifier)
        assert isinstance(v2.inner, HostBatchVerifier)  # CPU, no faults armed


class TestTableBuildBreaker:
    """The table-CONSTRUCTION path behind its own breaker (ROADMAP open
    item): a build fault must degrade — small sets host-build their
    tables, large sets answer with host crypto — never raise out of
    verify_commits. (The device verify kernel itself is exercised in the
    kernel-marked suites; these tests stay on the degradation paths.)"""

    def _commit_shape(self, n, corrupt=()):
        triples = _triples(n, corrupt=corrupt)
        pubs = [t[0] for t in triples]
        return pubs, [([t[1] for t in triples], [t[2] for t in triples])]

    def test_build_fault_host_builds_small_sets(self):
        from tendermint_tpu.services.verifier import TableBatchVerifier

        tv = TableBatchVerifier(min_device_batch=1)
        pubs, _ = self._commit_shape(3)
        fail.set_device_fault("tables", 1)
        tables, ok = tv._build_tables(tuple(pubs))  # degrades, no raise
        assert ok.all() and tables is not None
        snap = tv._build_breaker.snapshot()
        assert snap["total_failures"] == 1
        assert snap["state"] == CLOSED  # one fault < threshold

    def test_build_fault_on_large_set_degrades_to_host_crypto(self):
        from tendermint_tpu.services.verifier import TableBatchVerifier

        tv = TableBatchVerifier(min_device_batch=1)
        tv.MAX_INCREMENTAL_KEYS = 0  # every set counts as "too large"
        fail.set_device_fault("tables")  # forever, until cleared
        pubs, commits = self._commit_shape(3, corrupt=(1,))
        out = tv.verify_commits(pubs, commits)  # must not raise
        assert out.shape == (1, 3)
        assert bool(out[0, 0]) and not bool(out[0, 1]) and bool(out[0, 2])

    def test_open_build_breaker_stops_dialing_device_builds(self):
        from tendermint_tpu.services.verifier import (
            TableBatchVerifier,
            TableBuildError,
        )

        tv = TableBatchVerifier(min_device_batch=1)
        tv.MAX_INCREMENTAL_KEYS = 0
        tv._build_breaker = CircuitBreaker(
            failure_threshold=2, reset_timeout_s=60, name=None
        )
        fail.set_device_fault("tables")
        pubs, commits = self._commit_shape(2)
        tv.verify_commits(pubs, commits)
        tv.verify_commits(pubs, commits)
        assert tv._build_breaker.state == OPEN
        fail.clear_device_faults()
        # breaker OPEN: the device builder is not dialed at all, the
        # degradation answers immediately
        with pytest.raises(TableBuildError):
            tv._build_tables(tuple(pubs))
        out = tv.verify_commits(pubs, commits)  # still answers via host
        assert out.all()

    def test_table_build_telemetry_counters(self):
        from tendermint_tpu.services.verifier import TableBatchVerifier
        from tendermint_tpu.telemetry import REGISTRY

        tv = TableBatchVerifier(min_device_batch=1)
        before = REGISTRY.counter_value(
            "tendermint_verify_table_cache_total", event="host_build"
        )
        pubs, _ = self._commit_shape(2)
        fail.set_device_fault("tables", 1)
        tv._build_tables(tuple(pubs))
        assert (
            REGISTRY.counter_value(
                "tendermint_verify_table_cache_total", event="host_build"
            )
            == before + 1
        )
