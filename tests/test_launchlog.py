"""Device observatory (PR 13): LaunchLedger ring/persistence, the
ambient one-record-per-launch assembly through the dispatch and
coalescer seams, occupancy/padding accounting on the REAL mesh bucket
geometry, compile-cache and sharded-table placement-cache telemetry,
the `/health` device section, the `launches` dump view, and the live
4-node acceptance: every launch through the coalescing+resilient stack
yields exactly ONE ledger record, and `tools/device_report.py` over
`dump_telemetry?launches=N` names the top waste source."""

import json
import os
import sys
import threading
import time
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"),
)

from tendermint_tpu.telemetry import REGISTRY
from tendermint_tpu.telemetry import launchlog
from tendermint_tpu.telemetry.launchlog import LAUNCHLOG, LaunchLedger


@pytest.fixture(autouse=True)
def _ledger_reset():
    """Every test leaves the process-global ledger empty and the
    thread-ambient assembly state clean (the ledger is process-wide,
    like FLIGHT)."""
    LAUNCHLOG.clear()
    launchlog._tls.rec = None
    launchlog._tls.tags = None
    yield
    LAUNCHLOG.clear()
    launchlog._tls.rec = None
    launchlog._tls.tags = None


def _counter(name, **labels) -> float:
    return REGISTRY.counter_value(name, **labels)


def _make_sigs(n: int, salt: bytes = b"ll"):
    from tendermint_tpu.crypto.keys import gen_priv_key

    privs = [gen_priv_key(bytes([40 + i % 8]) * 32) for i in range(min(8, n))]
    msgs = [b'{"s":"%s","i":%d}' % (salt, i) for i in range(n)]
    sigs = [privs[i % len(privs)].sign(m) for i, m in enumerate(msgs)]
    pubs = [privs[i % len(privs)].pub_key.data for i in range(n)]
    return list(zip(pubs, msgs, sigs))


class TestLedger:
    def test_ring_bounded_and_ordered(self):
        led = LaunchLedger(capacity=4)
        for i in range(10):
            led.record({"kind": "verify", "rows": i})
        assert len(led) == 4
        assert [r["rows"] for r in led.recent()] == [6, 7, 8, 9]
        assert led.last()["rows"] == 9
        assert [r["rows"] for r in led.recent(2)] == [8, 9]

    def test_kind_filter(self):
        led = LaunchLedger(capacity=8)
        led.record({"kind": "verify", "rows": 1})
        led.record({"kind": "leaf_hashes", "rows": 2})
        assert [r["rows"] for r in led.recent(kind="leaf_hashes")] == [2]

    def test_jsonl_persist_and_reload(self, tmp_path):
        path = str(tmp_path / "launches.jsonl")
        led = LaunchLedger(path=path, capacity=8, node_id="n1")
        for i in range(3):
            led.record({"kind": "verify", "rows": i, "t": float(i)})
        led.close()
        reloaded = LaunchLedger(path=path, capacity=8)
        assert [r["rows"] for r in reloaded.recent()] == [0, 1, 2]
        assert reloaded.recent()[0]["node"] == "n1"
        reloaded.close()

    def test_compaction_bounds_the_file(self, tmp_path):
        path = str(tmp_path / "launches.jsonl")
        led = LaunchLedger(path=path, capacity=4)
        for i in range(20):
            led.record({"kind": "verify", "rows": i})
        led.close()
        with open(path) as f:
            lines = [ln for ln in f.readlines() if ln.strip()]
        # compaction trims to `capacity` whenever the file doubles past
        # it, so it can never exceed 2*capacity lines
        assert len(lines) <= 8

    def test_dump_all(self, tmp_path):
        LAUNCHLOG.record({"kind": "verify", "rows": 7})
        path = launchlog.dump_all(str(tmp_path), reason="test")
        assert path is not None and os.path.exists(path)
        with open(path) as f:
            payload = json.load(f)
        assert payload["reason"] == "test"
        assert payload["records"][-1]["rows"] == 7

    def test_env_disable(self, monkeypatch):
        monkeypatch.setenv("TENDERMINT_TPU_LAUNCHLOG", "0")
        assert launchlog.begin("verify") is None
        launchlog.annotate(rows_padded=5)
        launchlog.observe("verify", "mesh", 8, 0.01)
        assert len(LAUNCHLOG) == 0

    def test_seconds_since_success_tracks_errors(self):
        assert LAUNCHLOG.seconds_since_success() is None
        rec = launchlog.begin("verify")
        launchlog.commit(rec, error=RuntimeError("boom"))
        assert LAUNCHLOG.seconds_since_success() is None  # failed launch
        rec = launchlog.begin("verify")
        launchlog.commit(rec)
        age = LAUNCHLOG.seconds_since_success()
        assert age is not None and age < 5.0


class TestAmbientAssembly:
    def test_dispatch_handle_yields_one_record_with_stages(self):
        from tendermint_tpu.services.dispatch import DispatchQueue

        q = DispatchQueue(depth=2, name="launchlog-test")
        try:
            h = q.submit(
                lambda: launchlog.observe("verify", "mesh", 32, 0.001) or 41,
                lambda v: v + 1,
                kind="verify",
            )
            assert h.result(timeout=10) == 42
        finally:
            q.close()
        recs = LAUNCHLOG.recent()
        assert len(recs) == 1
        rec = recs[0]
        assert rec["kind"] == "verify" and rec["backend"] == "mesh"
        assert rec["rows"] == 32 and rec["queue"] == "launchlog-test"
        for stage in ("queue_wait_s", "host_prep_s", "in_flight_s",
                      "finalize_s", "total_s"):
            assert stage in rec, stage
        assert "error" not in rec
        # assembly-internal keys never leak into records
        assert not any(k.startswith("_") for k in rec)

    def test_launch_error_recorded(self):
        from tendermint_tpu.services.dispatch import DispatchQueue

        q = DispatchQueue(depth=1, name="launchlog-err")
        try:
            h = q.submit(lambda: 1 / 0, kind="hash")
            with pytest.raises(ZeroDivisionError):
                h.result(timeout=10)
        finally:
            q.close()
        recs = LAUNCHLOG.recent()
        assert len(recs) == 1
        assert recs[0]["error"] == "ZeroDivisionError"
        assert recs[0]["kind"] == "hash"

    def test_host_micro_call_outside_launch_records_nothing(self):
        launchlog.observe("verify", "host", 1, 0.0001)
        assert len(LAUNCHLOG) == 0

    def test_sync_device_call_records_standalone(self):
        launchlog.observe("tables", "tables", 256, 0.05)
        recs = LAUNCHLOG.recent()
        assert len(recs) == 1
        assert recs[0]["kind"] == "tables" and recs[0]["rows"] == 256

    def test_implicit_record_from_annotation_commits_at_observe(self):
        # the synchronous-launch shape: padding annotated during lane
        # prep, the backend's observe closes the record
        launchlog.annotate(_additive=True, rows_padded=31)
        launchlog.add_transfer(4096)
        launchlog.observe("verify", "mesh", 33, 0.02)
        recs = LAUNCHLOG.recent()
        assert len(recs) == 1
        assert recs[0]["rows"] == 33 and recs[0]["rows_padded"] == 31
        assert recs[0]["transfer_bytes"] == 4096
        assert launchlog.current() is None

    def test_tags_cross_the_dispatch_thread(self):
        from tendermint_tpu.services.dispatch import DispatchQueue

        q = DispatchQueue(depth=1, name="launchlog-tags")
        try:
            with launchlog.tag(
                consumers={"consensus": 8, "mempool": 4}, rows_cached=3
            ):
                h = q.submit(
                    lambda: launchlog.observe("verify", "mesh", 12, 0.001),
                    kind="verify",
                )
            h.result(timeout=10)
        finally:
            q.close()
        rec = LAUNCHLOG.recent()[0]
        assert rec["consumers"] == {"consensus": 8, "mempool": 4}
        assert rec["rows_cached"] == 3
        # the tag context has exited: later submits carry nothing
        assert launchlog.current_tags() is None

    def test_trace_exemplar_rides_the_record(self):
        from tendermint_tpu.services.dispatch import DispatchQueue
        from tendermint_tpu.telemetry import tracectx as _tc

        ctx = _tc.TraceContext(os.urandom(8), os.urandom(8), "launch-test")
        q = DispatchQueue(depth=1, name="launchlog-trace")
        try:
            with _tc.use(ctx):
                h = q.submit(lambda: None, kind="verify")
            h.result(timeout=10)
        finally:
            q.close()
        assert LAUNCHLOG.recent()[0]["trace"] == ctx.trace

    def test_metrics_observed_at_commit(self):
        u0 = _counter("tendermint_launch_rows", kind="verify", state="useful")
        p0 = _counter("tendermint_launch_rows", kind="verify", state="padded")
        rec = launchlog.begin("verify")
        rec["queue_wait_s"] = 0.001
        launchlog.annotate(_additive=True, rows_padded=7)
        launchlog.observe("verify", "mesh", 9, 0.01)
        launchlog.commit(rec)
        assert (
            _counter("tendermint_launch_rows", kind="verify", state="useful") - u0
            == 9
        )
        assert (
            _counter("tendermint_launch_rows", kind="verify", state="padded") - p0
            == 7
        )


def _host_mesh_verifier(n_devices: int):
    import jax

    from tendermint_tpu.parallel.mesh import MeshManager
    from tendermint_tpu.services.verifier import ShardedBatchVerifier

    mgr = MeshManager(
        devices=list(jax.devices())[:n_devices], executor="host"
    )
    return ShardedBatchVerifier(mesh=mgr, min_device_batch=1), mgr


class TestOccupancyAccounting:
    """The waste math on the REAL mesh pad geometry (per-chip
    power-of-two bucket x active width, `_mesh_flat_launch`), via the
    host-executor mesh — no XLA compile, identical shapes."""

    def test_exact_fit_no_padding(self):
        v, mgr = _host_mesh_verifier(4)
        triples = _make_sigs(32, b"fit")  # 8/chip = the minimum bucket
        assert bool(v.verify_batch(triples).all())
        rec = LAUNCHLOG.recent(kind="verify")[-1]
        assert rec["rows"] == 32
        assert rec.get("rows_padded", 0) == 0
        assert rec["mesh_width"] == 4
        assert rec["backend"] == "mesh"

    def test_bucket_boundary_cross_pads(self):
        v, mgr = _host_mesh_verifier(4)
        # 33 rows / 4 chips -> 9/chip -> bucket 16 -> 64 shipped rows
        triples = _make_sigs(33, b"cross")
        assert bool(v.verify_batch(triples).all())
        rec = LAUNCHLOG.recent(kind="verify")[-1]
        assert rec["rows"] == 33
        assert rec["rows_padded"] == 64 - 33
        # transfer: 4 x (64,32) u8 lane arrays + (64,) i32 powers
        assert rec["transfer_bytes"] == 4 * 64 * 32 + 64 * 4
        summary = launchlog.summarize([rec])["verify"]
        assert summary["occupancy_pct"] == round(100.0 * 33 / 64, 1)
        assert summary["padding_waste_pct"] == round(100.0 * 31 / 64, 1)

    def test_non_divisible_row_count(self):
        v, mgr = _host_mesh_verifier(4)
        triples = _make_sigs(10, b"odd")  # ceil(10/4)=3 -> bucket 8 -> 32
        assert bool(v.verify_batch(triples).all())
        rec = LAUNCHLOG.recent(kind="verify")[-1]
        assert rec["rows"] == 10 and rec["rows_padded"] == 22

    def test_rows_counters_advance(self):
        u0 = _counter("tendermint_launch_rows", kind="verify", state="useful")
        p0 = _counter("tendermint_launch_rows", kind="verify", state="padded")
        v, mgr = _host_mesh_verifier(4)
        assert bool(v.verify_batch(_make_sigs(10, b"ctr")).all())
        assert (
            _counter("tendermint_launch_rows", kind="verify", state="useful")
            - u0
            == 10
        )
        assert (
            _counter("tendermint_launch_rows", kind="verify", state="padded")
            - p0
            == 22
        )


class TestCacheFilteredLanes:
    def test_coalesced_flush_carries_cache_withholding_and_mix(self):
        from tendermint_tpu.services.batcher import CoalescingVerifier
        from tendermint_tpu.services.verifier import HostBatchVerifier

        v = CoalescingVerifier(
            HostBatchVerifier(), cache_size=1024, window_s=0.5
        )
        try:
            known = _make_sigs(6, b"known")
            novel = _make_sigs(4, b"novel")
            # prime: prove the known triples (positives enter the cache)
            assert bool(v.verify_batch(known).all())
            n_before = len(LAUNCHLOG)
            # mixed offer: 6 cached lanes withheld, 4 novel dispatched;
            # the barrier join forces the flush
            h = v.verify_batch_async(known + novel, consumer="consensus")
            assert bool(h.result(timeout=10).all())
            recs = LAUNCHLOG.recent()[n_before:]
            assert len(recs) == 1, recs
            rec = recs[0]
            assert rec["rows"] == 4  # only the novel lanes launched
            assert rec["rows_cached"] == 6
            assert rec["consumers"] == {"consensus": 4}
            assert rec["requests"] == 1
        finally:
            v.close()

    def test_fully_cached_offer_launches_nothing(self):
        from tendermint_tpu.services.batcher import CoalescingVerifier
        from tendermint_tpu.services.verifier import HostBatchVerifier

        v = CoalescingVerifier(
            HostBatchVerifier(), cache_size=1024, window_s=0.001
        )
        try:
            triples = _make_sigs(5, b"allcached")
            assert bool(v.verify_batch(triples).all())
            n_before = len(LAUNCHLOG)
            h = v.verify_batch_async(triples, consumer="rpc")
            assert bool(h.result(timeout=10).all())
            assert len(LAUNCHLOG) == n_before  # no launch, no record
        finally:
            v.close()

    def test_commit_grid_cached_lanes_reduce_requested_rows(self):
        """Cached commit-grid lanes are withheld from the inner backend
        and tagged onto its launch record (the sync tables shape)."""
        from tendermint_tpu.services.batcher import CoalescingVerifier
        from tendermint_tpu.services.verifier import (
            BatchVerifier,
            HostBatchVerifier,
            _observe_verify,
        )

        class GridBackend(BatchVerifier):
            """Backend with a commit-grid surface that reports itself
            like the real table path (kind=tables)."""

            def __init__(self):
                super().__init__()
                self._host = HostBatchVerifier()

            def verify_batch(self, triples):
                return self._host.verify_batch(triples)

            def verify_commits(self, pubkeys, commits, force_fused=None):
                n = len(pubkeys)
                out = np.zeros((len(commits), n), dtype=bool)
                lanes = 0
                for ci, (msgs, sigs) in enumerate(commits):
                    for i in range(n):
                        if msgs[i] is not None and sigs[i] is not None:
                            lanes += 1
                            out[ci, i] = bool(
                                self._host.verify_batch(
                                    [(pubkeys[i], msgs[i], sigs[i])]
                                )[0]
                            )
                _observe_verify("tables", lanes, 0.001, kind="tables")
                return out

        v = CoalescingVerifier(GridBackend(), cache_size=1024, window_s=0.5)
        try:
            triples = _make_sigs(4, b"grid")
            pubkeys = [pk for pk, _m, _s in triples]
            msgs = [m for _pk, m, _s in triples]
            sigs = [s for _pk, _m, s in triples]
            commit = (list(msgs), list(sigs))
            grid1 = v.verify_commits(pubkeys, [commit])
            assert bool(grid1.all())
            first = LAUNCHLOG.recent(kind="tables")[-1]
            assert first["rows"] == 4 and first.get("rows_cached", 0) == 0
            # second pass: every lane proven -> withheld entirely
            n_before = len(LAUNCHLOG)
            grid2 = v.verify_commits(pubkeys, [commit])
            assert bool(grid2.all())
            assert len(LAUNCHLOG) == n_before  # no novel lanes, no launch
            # third pass: one lane evicted from the cache -> partial
            from tendermint_tpu.services.batcher import VerifiedSigCache

            key = VerifiedSigCache.key(pubkeys[0], msgs[0], sigs[0])
            lock, od = v.cache._shard(key)
            with lock:
                od.pop(key, None)
            grid3 = v.verify_commits(pubkeys, [commit])
            assert bool(grid3.all())
            rec = LAUNCHLOG.recent(kind="tables")[-1]
            assert rec["rows"] == 1 and rec["rows_cached"] == 3
        finally:
            v.close()


class TestCompileCacheTelemetry:
    def test_pre_seeded_from_boot(self):
        # the M001 catalog lint + dashboards see zero-valued series
        # before any compile/placement happens
        for result in ("hit", "miss"):
            assert (
                _counter("tendermint_mesh_compile_total", result=result) >= 0
            )
            assert (
                _counter("tendermint_table_device_cache_total", result=result)
                >= 0
            )
        for kind in ("verify", "hash", "tables", "leaf_hashes"):
            for state in ("useful", "padded", "cached"):
                assert (
                    _counter("tendermint_launch_rows", kind=kind, state=state)
                    >= 0
                )

    def test_step_cache_miss_then_hit(self):
        import jax

        from tendermint_tpu.parallel import mesh as mesh_mod

        mgr = mesh_mod.MeshManager(
            devices=list(jax.devices())[:2], executor="host"
        )
        program = f"launchlog-test-{time.monotonic_ns()}"
        seen_in_progress = []

        def build():
            seen_in_progress.append(mesh_mod.compiles_in_progress())
            time.sleep(0.01)
            return "compiled-step"

        m0 = _counter("tendermint_mesh_compile_total", result="miss")
        h0 = _counter("tendermint_mesh_compile_total", result="hit")
        rec = launchlog.begin("verify")
        step = mgr._cached_step(program, build)
        assert step == "compiled-step"
        assert seen_in_progress == [1]
        assert mesh_mod.compiles_in_progress() == 0
        assert _counter("tendermint_mesh_compile_total", result="miss") - m0 == 1
        assert rec["compile"] == "miss" and rec["compile_s"] > 0
        # second lookup: hit, no rebuild, annotated as such
        step2 = mgr._cached_step(program, lambda: pytest.fail("rebuilt"))
        assert step2 == "compiled-step"
        assert _counter("tendermint_mesh_compile_total", result="hit") - h0 == 1
        assert rec["compile"] == "hit"
        launchlog.commit(rec)

    def test_sharded_table_placement_cache(self, monkeypatch):
        import jax
        import jax.numpy as jnp

        from tendermint_tpu.parallel.mesh import MeshManager
        from tendermint_tpu.services.verifier import ShardedTableBatchVerifier

        mgr = MeshManager(devices=list(jax.devices())[:2], executor="host")
        v = ShardedTableBatchVerifier(mesh=mgr, min_device_batch=1)
        tables = jnp.zeros((2, 2, 2, 4), dtype=jnp.int16)
        key_ok = np.ones(4, dtype=bool)
        monkeypatch.setattr(v, "_tables_for", lambda pubs: (tables, key_ok))
        pubs = tuple(bytes([i]) * 32 for i in range(4))
        m0 = _counter("tendermint_table_device_cache_total", result="miss")
        h0 = _counter("tendermint_table_device_cache_total", result="hit")
        rec = launchlog.begin("tables")
        v._tables_for_mesh(pubs, mgr.mesh())
        assert (
            _counter("tendermint_table_device_cache_total", result="miss") - m0
            == 1
        )
        # the miss pays a device_put: bytes + stall on the record
        assert rec["transfer_bytes"] == tables.nbytes
        assert rec["device_put_s"] >= 0
        v._tables_for_mesh(pubs, mgr.mesh())
        assert (
            _counter("tendermint_table_device_cache_total", result="hit") - h0
            == 1
        )
        launchlog.commit(rec)


def _stub_node(**over):
    from tendermint_tpu.telemetry.heightlog import HeightLedger

    ledger = HeightLedger()
    now = time.time()
    for h in (1, 2, 3):
        ledger.record(
            {"height": h, "finality_s": 0.2 if h > 1 else None, "t_commit": now}
        )
    verifier = over.pop(
        "verifier", SimpleNamespace(snapshot=lambda: {"state": "closed"})
    )
    return SimpleNamespace(
        node_id="stub",
        consensus=SimpleNamespace(verifier=verifier, fatal_error=None),
        blockchain_reactor=SimpleNamespace(fast_sync=False),
        statesync_reactor=None,
        switch=SimpleNamespace(n_peers=lambda: 3),
        block_store=SimpleNamespace(height=3),
        hasher=None,
        height_ledger=ledger,
    )


class TestHealthDeviceSection:
    def test_device_section_reported_not_folded(self):
        from tendermint_tpu.telemetry.health import build_health

        node = _stub_node(
            verifier=SimpleNamespace(
                snapshot=lambda: {
                    "state": "closed",
                    "mesh": {"devices_active": 3, "devices_total": 4},
                }
            )
        )
        h = build_health(node)
        dev = h["device"]
        assert dev["mesh_active"] == 3 and dev["mesh_total"] == 4
        assert dev["compile_in_progress"] is False
        # mesh *degradation* folds via the mesh check, the device
        # section itself never does — and a quiet launch ledger must
        # not change the status either
        assert h["status"] == "degraded"  # from the mesh check, 3 < 4
        assert not h["checks"]["mesh"]["ok"]

    def test_last_launch_age(self):
        from tendermint_tpu.telemetry.health import build_health

        h = build_health(_stub_node())
        assert h["device"]["last_launch_age_s"] is None
        rec = launchlog.begin("verify")
        launchlog.observe("verify", "mesh", 8, 0.001)
        launchlog.commit(rec)
        h = build_health(_stub_node())
        assert h["device"]["last_launch_age_s"] is not None
        assert h["device"]["last_launch_age_s"] < 5.0
        assert h["status"] == "ok"

    def test_meshless_node_reports_none_widths(self):
        from tendermint_tpu.telemetry.health import build_health

        h = build_health(_stub_node())
        assert h["device"]["mesh_active"] is None
        assert h["device"]["mesh_total"] is None


class TestLaunchesView:
    def test_view_returns_records_and_summary(self):
        from tendermint_tpu.telemetry import views

        launchlog.annotate(_additive=True, rows_padded=2)
        launchlog.observe("verify", "mesh", 6, 0.01)
        out = views.collect(_stub_node(), [("launches", {"n": 10})])
        assert "launches" in out
        view = out["launches"]
        assert view["records"][-1]["rows"] == 6
        assert view["summary"]["verify"]["rows"] == 6
        assert view["summary"]["verify"]["rows_padded"] == 2

    def test_collect_plain_names_still_work(self):
        from tendermint_tpu.telemetry import views

        out = views.collect(_stub_node(), ["launches"])
        assert "launches" in out


class TestDeviceReport:
    def _records(self):
        t = 1000.0
        out = []
        for i in range(4):
            out.append(
                {
                    "t": t + 0.1 * i,  # near back-to-back: idle stays small
                    "kind": "verify",
                    "backend": "mesh",
                    "queue": "coalescer",
                    "node": "n0",
                    "rows": 96,
                    "rows_padded": 32,
                    "rows_cached": 16,
                    "mesh_width": 8,
                    "transfer_bytes": 16384,
                    "consumers": {"consensus": 64, "mempool": 32},
                    "queue_wait_s": 0.001,
                    "host_prep_s": 0.004,
                    "in_flight_s": 0.080,
                    "finalize_s": 0.002,
                    "total_s": 0.087,
                }
            )
        out.append(
            {
                "t": t + 10,
                "kind": "tables",
                "backend": "mesh",
                "queue": "default",
                "node": "n0",
                "rows": 512,
                "rows_padded": 0,
                "compile": "miss",
                "compile_s": 2.5,
                "device_put_s": 0.4,
                "transfer_bytes": 1 << 20,
                "in_flight_s": 0.05,
                "total_s": 2.6,
            }
        )
        return out

    def test_waterfall_and_verdict(self):
        import device_report as dr

        report = dr.build_report(self._records())
        assert report["launches"] == 5
        verify = report["kinds"]["verify"]
        assert verify["launches"] == 4
        assert verify["occupancy_pct"] == 75.0
        assert verify["padding_waste_pct"] == 25.0
        assert verify["cache_withheld_pct"] == round(
            100.0 * 64 / (4 * 96 + 64), 1
        )
        assert verify["consumers"] == {"consensus": 256, "mempool": 128}
        tables = report["kinds"]["tables"]
        assert tables["compile_misses"] == 1 and tables["compile_s"] == 2.5
        # the 2.5s compile stall dominates every other waste source
        assert report["verdict"]["top_waste_source"] == "compile_stalls"
        text = dr.render_text(report)
        assert "compile_stalls" in text and "verdict:" in text
        assert "consumers: consensus 256, mempool 128" in text

    def test_padding_verdict_when_padding_dominates(self):
        import device_report as dr

        recs = [
            {
                "t": 1000.0 + i,
                "kind": "verify",
                "rows": 8,
                "rows_padded": 120,
                "in_flight_s": 1.0,
                "total_s": 1.1,
                "queue": "coalescer",
            }
            for i in range(3)
        ]
        report = dr.build_report(recs)
        assert report["verdict"]["top_waste_source"] == "padding_waste"
        assert "reseed" in report["verdict"]["reseed_note"]

    def test_load_ledgers_jsonl_and_dump_dedupe(self, tmp_path):
        import device_report as dr

        recs = self._records()
        jsonl = tmp_path / "launches.jsonl"
        with open(jsonl, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        dump = tmp_path / "launchledger-test-1.json"
        with open(dump, "w") as f:
            json.dump({"reason": "test", "records": recs[:2]}, f)
        loaded = dr.load_ledgers([str(jsonl), str(dump)])
        assert len(loaded) == len(recs)  # overlap deduped

    def test_empty_report_has_no_verdict(self):
        import device_report as dr

        report = dr.build_report([])
        assert report["verdict"] is None
        assert "no launches recorded" in dr.render_text(report)


def _rpc(port, method, **params):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        out = json.load(resp)
    if "error" in out:
        raise RuntimeError(out["error"])
    return out["result"]


def _coalescing_factory():
    """The production default-verifier SHAPE on CPU: coalescer + dedup
    cache over a resilient host stack — the wrappers the no-double-count
    acceptance is about."""
    from tendermint_tpu.services.batcher import CoalescingVerifier
    from tendermint_tpu.services.resilient import ResilientVerifier
    from tendermint_tpu.services.verifier import HostBatchVerifier

    def factory(_i):
        return CoalescingVerifier(
            ResilientVerifier(HostBatchVerifier(), max_retries=0),
            cache_size=4096,
        )

    return factory


class TestDeviceObservatoryAcceptance:
    """ISSUE 13 acceptance: a live 4-node net under loadgen traffic —
    every launch through the coalescing/resilient verify stack yields
    exactly one ledger record (records == coalesced launches, no
    double-count through the wrappers), the hash lane records through
    the same seam, and `tools/device_report.py` over
    `dump_telemetry?launches=N` produces the per-kind waterfall and
    names the top waste source."""

    def test_live_net_loadgen_device_report(self, tmp_path):
        import itertools

        import device_report as dr

        from tendermint_tpu.crypto.keys import gen_priv_key
        from tendermint_tpu.mempool import make_signed_tx
        from tendermint_tpu.testing.nemesis import Nemesis

        priv = gen_priv_key(b"\x66" * 32)
        # baseline BEFORE the net exists: every coalesced flush from
        # here on is counted on both sides (no mid-flight boundary)
        fam = REGISTRY.get("tendermint_batcher_coalesce_factor")
        coalesce0 = fam._child0().value["count"]
        with Nemesis(
            4,
            home=str(tmp_path),
            node_factory=Nemesis.full_node_factory(),
            verifier_factory=_coalescing_factory(),
        ) as net:
            net.wait_height(2, timeout=90)
            stop = threading.Event()
            seq = itertools.count()

            def pump():
                for i in seq:
                    if stop.is_set() or i >= 600:
                        return
                    tx = make_signed_tx(priv, b"dev-%d=%d" % (i, i))
                    net.nodes[i % 2].node.mempool.check_tx_async(
                        tx, lambda res: None
                    )
                    time.sleep(0.003)

            pump_thread = threading.Thread(target=pump, daemon=True)
            pump_thread.start()
            try:
                net.wait_progress(delta=3, timeout=120)
            finally:
                stop.set()
                pump_thread.join(10)

            # hash lane through the same dispatch seam: one async
            # leaf-hash launch -> exactly one leaf_hashes record
            from tendermint_tpu.services.hasher import TreeHasher
            from tendermint_tpu.services.resilient import ResilientTreeHasher

            hasher = ResilientTreeHasher(
                TreeHasher(backend="host"), TreeHasher(backend="host")
            )
            leaf0 = len(LAUNCHLOG.recent(kind="leaf_hashes"))
            out = hasher.leaf_hashes_async(
                [b"leaf-%d" % i for i in range(64)]
            ).result(timeout=30)
            assert len(out) == 64
            assert len(LAUNCHLOG.recent(kind="leaf_hashes")) == leaf0 + 1

            # quiesce: traffic stopped; wait until records catch the
            # flush counter (records commit at join, a beat after the
            # flush observes) and compare the matched snapshot —
            # consensus keeps committing empty heights, so a stale
            # re-read would race a fresh flush
            deadline = time.monotonic() + 30
            launches = 0
            recs: list = []
            while time.monotonic() < deadline:
                launches = fam._child0().value["count"] - coalesce0
                recs = [
                    r
                    for r in LAUNCHLOG.recent()
                    if r.get("queue") == "coalescer"
                ]
                if launches > 0 and len(recs) == launches:
                    break
                time.sleep(0.25)
            assert launches > 0, "no coalesced launches under loadgen?"
            # EXACTLY one ledger record per coalesced launch: the
            # resilient wrapper inside and the coalescer outside never
            # double-count
            assert len(recs) == launches, (len(recs), launches)
            for rec in recs:
                assert rec["kind"] == "verify"
                assert rec["backend"] == "host"  # CPU net: host executes
                assert rec["rows"] > 0
                assert rec["consumers"], rec

            # the report, over the RPC dump of a live node
            dump = _rpc(
                net.nodes[0].rpc_port,
                "dump_telemetry",
                spans=0,
                launches=512,
            )
            view = dump["launches"]
            assert view["records"], "dump served no launch records"
            assert "verify" in view["summary"]
            report = dr.build_report(view["records"])
            assert report["launches"] > 0
            assert "verify" in report["kinds"]
            assert report["verdict"] is not None
            assert report["verdict"]["top_waste_source"] in dr._FIXES
            text = dr.render_text(report)
            assert "device observatory" in text and "verdict:" in text

            # health: the device section is served on the live node
            with urllib.request.urlopen(
                f"http://127.0.0.1:{net.nodes[0].rpc_port}/health", timeout=10
            ) as resp:
                health = json.load(resp)
            assert "device" in health
            assert health["device"]["last_launch_age_s"] is not None
