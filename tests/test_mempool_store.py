"""Mempool behavior + BlockStore round-trips."""

import threading

import pytest

from tendermint_tpu.abci.apps import CounterApp, KVStoreApp
from tendermint_tpu.abci.client import local_client_creator
from tendermint_tpu.blockchain import BlockStore
from tendermint_tpu.db.kv import MemDB
from tendermint_tpu.mempool import Mempool, TxCache
from tendermint_tpu.types.errors import ValidationError
from tendermint_tpu.types.tx import Txs

from tests.helpers import ChainSim


class TestTxCache:
    def test_dedup_and_eviction(self):
        c = TxCache(size=2)
        assert c.push(b"a") and not c.push(b"a")
        c.push(b"b")
        c.push(b"c")  # evicts a
        assert c.push(b"a")

    def test_remove(self):
        c = TxCache(size=4)
        c.push(b"a")
        c.remove(b"a")
        assert c.push(b"a")


def _mempool(app=None, **kw):
    conns = local_client_creator(app or KVStoreApp())()
    return Mempool(conns.mempool, **kw), conns


class TestMempool:
    def test_check_reap_update(self):
        mp, _ = _mempool()
        for i in range(5):
            mp.check_tx(b"k%d=v%d" % (i, i))
        assert mp.size() == 5
        dup = mp.check_tx(b"k0=v0")
        assert dup.log == "tx already exists in cache"
        assert not dup.is_ok  # duplicates are a visible rejection (ErrTxInCache)
        assert mp.size() == 5
        reaped = mp.reap(3)
        assert len(reaped) == 3
        assert len(mp.reap(-1)) == 5
        mp.update(1, Txs([b"k0=v0", b"k1=v1"]))
        assert mp.size() == 3

    def test_bad_tx_rejected_and_uncached(self):
        app = CounterApp(serial=True)
        mp, conns = _mempool(app)
        mp.check_tx((5).to_bytes(2, "big"))
        assert mp.size() == 1
        # nonce 0 < tx_count after deliver? deliver 6 txs via consensus conn
        for i in range(6):
            conns.consensus.deliver_tx_async(i.to_bytes(1, "big") if i else b"")
        mp.check_tx((2).to_bytes(1, "big"))  # nonce 2 < 6: rejected
        assert mp.size() == 1
        # rejected tx was evicted from the cache, so it can be retried
        assert mp.check_tx((2).to_bytes(1, "big")).code != 0

    def test_update_recheck_drops_stale(self):
        app = CounterApp(serial=True)
        mp, conns = _mempool(app)
        for i in range(3):
            mp.check_tx(i.to_bytes(1, "big") if i else b"\x00")
        assert mp.size() == 3
        # app advances past nonce 1 -> txs 0,1 now stale
        conns.consensus.deliver_tx_async(b"\x00")
        conns.consensus.deliver_tx_async(b"\x01")
        mp.update(1, Txs())
        assert mp.size() == 1  # only nonce-2 tx survives recheck

    def test_txs_available_fires_once_per_height(self):
        mp, _ = _mempool()
        fired = []
        mp.set_on_txs_available(lambda: fired.append(1))
        mp.check_tx(b"a=1")
        mp.check_tx(b"b=2")
        assert len(fired) == 1
        mp.update(1, Txs([b"a=1"]))  # pool still has b=2 -> fires again
        assert len(fired) == 2

    def test_wal_replay(self, tmp_path):
        mp, _ = _mempool(wal_dir=str(tmp_path))
        mp.check_tx(b"x=1")
        mp.check_tx(b"y=2")
        assert mp.load_wal() == [b"x=1", b"y=2"]
        mp.close()

    def test_get_after_blocks_until_new_tx(self):
        mp, _ = _mempool()
        mp.check_tx(b"a=1")
        got = mp.get_after(0)
        assert got == [(1, b"a=1")]
        results = []

        def waiter():
            results.extend(mp.get_after(1, wait=True, timeout=5))

        t = threading.Thread(target=waiter)
        t.start()
        mp.check_tx(b"b=2")
        t.join(timeout=5)
        assert results == [(2, b"b=2")]

    def test_get_after_counter_survives_commit_compaction(self):
        # positional cursors would stall after update() compacts the
        # list (round-3 review finding): counters must keep advancing
        from tendermint_tpu.types.tx import Txs

        mp, _ = _mempool()
        mp.check_tx(b"a=1")
        mp.check_tx(b"b=2")
        cursor = max(c for c, _ in mp.get_after(0))
        assert cursor == 2
        mp.lock()
        try:
            mp.update(1, Txs([b"a=1", b"b=2"]))  # both committed
        finally:
            mp.unlock()
        mp.check_tx(b"c=3")
        got = mp.get_after(cursor)
        assert got == [(3, b"c=3")]


class TestBlockStore:
    def _chain(self, n=3):
        sim = ChainSim(n_vals=3)
        store = BlockStore(MemDB())
        for i in range(n):
            block, ps = sim.make_next_block(txs=[b"t%d=%d" % (i, i)])
            commit = sim._commit_for(block, ps)
            from tendermint_tpu.state import apply_block

            apply_block(sim.state, block, ps.header, sim.conns.consensus)
            sim.blocks.append(block)
            sim.commits.append(commit)
            store.save_block(block, ps, commit)
        return sim, store

    def test_save_load_roundtrip(self):
        sim, store = self._chain(3)
        assert store.height == 3
        for h in (1, 2, 3):
            blk = store.load_block(h)
            assert blk is not None and blk.hash() == sim.blocks[h - 1].hash()
            meta = store.load_block_meta(h)
            assert meta.header.height == h
            assert meta.block_id.hash == blk.hash()
        # canonical commit for h is carried by block h+1
        c2 = store.load_block_commit(2)
        assert c2.hash() == sim.blocks[2].last_commit.hash()
        sc3 = store.load_seen_commit(3)
        assert sc3.hash() == sim.commits[2].hash()
        assert store.load_block(4) is None
        assert store.load_block_commit(99) is None

    def test_parts_individually_loadable(self):
        sim, store = self._chain(1)
        meta = store.load_block_meta(1)
        total = meta.block_id.parts_header.total
        buf = b""
        for i in range(total):
            part = store.load_block_part(1, i)
            assert part is not None and part.index == i
            buf += part.bytes_
        from tendermint_tpu.types.block import Block

        assert Block.decode(buf).hash() == sim.blocks[0].hash()
        assert store.load_block_part(1, total) is None

    def test_noncontiguous_save_rejected(self):
        sim, store = self._chain(1)
        block, ps = sim.make_next_block()
        block.header.height = 5
        with pytest.raises(ValidationError, match="contiguous"):
            store.save_block(block, ps, sim.commits[-1])

    def test_reload_watermark(self):
        db = MemDB()
        sim = ChainSim(n_vals=3)
        store = BlockStore(db)
        block, ps = sim.make_next_block()
        commit = sim._commit_for(block, ps)
        from tendermint_tpu.state import apply_block

        apply_block(sim.state, block, ps.header, sim.conns.consensus)
        store.save_block(block, ps, commit)
        store2 = BlockStore(db)
        assert store2.height == 1
        assert store2.load_block(1).hash() == block.hash()


def test_replay_wal_recovers_and_compacts(tmp_path):
    mp, _ = _mempool(wal_dir=str(tmp_path))
    mp.check_tx(b"a=1")
    mp.check_tx(b"b=2")
    mp.close()
    # restart: fresh mempool over the same WAL dir
    mp2, _ = _mempool(wal_dir=str(tmp_path))
    n = mp2.replay_wal()
    assert n == 2
    assert {bytes(t) for t in mp2.reap(-1)} == {b"a=1", b"b=2"}
    # compaction: a second restart replays the same two, not four
    mp2.close()
    mp3, _ = _mempool(wal_dir=str(tmp_path))
    assert mp3.load_wal() == [b"a=1", b"b=2"]
    mp3.close()
