"""Light-client serving layer: bisection certifier, certified-commit
cache/store, 0x68 reactor, replica mode, forged-FullCommit attribution
(tendermint_tpu/lightclient/, PR 15 / ROADMAP item 1).
"""

import threading
import time

import pytest

from tendermint_tpu.certifiers.provider import MemProvider
from tendermint_tpu.db.fullcommit import FullCommitStore
from tendermint_tpu.db.kv import MemDB
from tendermint_tpu.lightclient import (
    BisectingCertifier,
    CertifiedCommitCache,
    extract_double_sign_evidence,
)
from tendermint_tpu.types.errors import (
    ErrNoSourceCommit,
    ErrTooMuchChange,
    ErrTrustExpired,
    ValidationError,
)

from tests.test_certifiers import _full_commit, _privs, _valset

CHAIN = "light-chain"


def _chain_source(heights, privs_for):
    """MemProvider of FullCommits: privs_for(h) -> priv list at h."""
    src = MemProvider()
    fcs = {}
    for h in heights:
        fcs[h] = _full_commit(h, privs_for(h))
        src.store_commit(fcs[h])
    return src, fcs


class TestFullCommitStore:
    def test_roundtrip_floor_exact_latest(self):
        store = FullCommitStore(MemDB())
        privs = _privs(range(1, 5))
        for h in (2, 5, 9):
            store.store_commit(_full_commit(h, privs))
        assert store.get_by_height(1) is None
        assert store.get_by_height(5).height() == 5
        assert store.get_by_height(8).height() == 5
        assert store.get_exact(5).height() == 5
        assert store.get_exact(6) is None
        assert store.latest_commit().height() == 9
        assert store.latest_height() == 9
        assert len(store) == 3

    def test_survives_reopen(self):
        db = MemDB()
        store = FullCommitStore(db)
        privs = _privs(range(1, 5))
        fc = _full_commit(12, privs)
        store.store_commit(fc)
        again = FullCommitStore(db)  # fresh index over the same DB
        got = again.get_by_height(100)
        assert got.height() == 12
        assert got.header.hash() == fc.header.hash()
        assert got.validators.hash() == fc.validators.hash()

    def test_prune_keeps_recent(self):
        store = FullCommitStore(MemDB())
        privs = _privs(range(1, 5))
        for h in range(1, 11):
            store.store_commit(_full_commit(h, privs))
        assert store.prune(3) == 7
        assert store.heights() == [8, 9, 10]
        assert store.get_by_height(7) is None
        assert store.get_by_height(9).height() == 9


class TestCertifiedCommitCache:
    def test_positives_only_surface(self):
        """The ONLY write path is put_certified/store_commit — there is
        no API to record a rejection, so a forged commit re-verifies on
        every offer (the VerifiedSigCache discipline)."""
        cache = CertifiedCommitCache()
        assert not hasattr(cache, "put_rejected")
        assert cache.get_exact(5) is None  # miss, nothing pinned
        privs = _privs(range(1, 5))
        cache.put_certified(_full_commit(5, privs))
        assert cache.get_exact(5).height() == 5
        assert cache.get_by_height(9).height() == 5
        assert cache.get_by_height(4) is None

    def test_eviction_oldest_first(self):
        cache = CertifiedCommitCache(capacity=3)
        privs = _privs(range(1, 5))
        for h in range(1, 6):
            cache.put_certified(_full_commit(h, privs))
        assert len(cache) == 3
        assert cache.get_exact(1) is None
        assert cache.get_exact(5).height() == 5

    def test_store_fallback_readmission_stays_evictable(self):
        """A store-backed hit re-admitted to the hot tier must re-enter
        the height index — otherwise the evictor (which only drops
        heights popped from the index) never sees it and shard dicts
        grow without bound under historical-read workloads."""
        store = FullCommitStore(MemDB())
        privs = _privs(range(1, 5))
        for h in range(1, 11):
            store.store_commit(_full_commit(h, privs))
        cache = CertifiedCommitCache(capacity=3, store=store)
        for h in range(1, 11):
            assert cache.get_exact(h).height() == h  # store fallback
        shard_entries = sum(len(entries) for _, entries in cache._shards)
        assert shard_entries <= 3
        assert len(cache) <= 3

    def test_write_through_store_and_warm_reload(self):
        db = MemDB()
        cache = CertifiedCommitCache(store=FullCommitStore(db))
        privs = _privs(range(1, 5))
        cache.put_certified(_full_commit(7, privs))
        # a fresh cache over the same DB reloads proven trust
        cache2 = CertifiedCommitCache(store=FullCommitStore(db))
        assert cache2.latest_height() == 7
        assert cache2.get_exact(7).height() == 7
        stats = cache2.stats()
        assert stats["entries"] == 1 and stats["latest_height"] == 7


class TestBisectionMath:
    def test_stable_valset_single_round(self):
        """A 256-height jump over an unchanged valset is ONE combined
        round and at most a couple dozen commit verifies (the probe
        ladder rides a single launch) — the acceptance criterion's
        shape."""
        privs = _privs(range(1, 5))
        src, fcs = _chain_source((1, 64, 128, 200, 256), lambda h: privs)
        cert = BisectingCertifier(
            CHAIN, seed=fcs[1], trusted=MemProvider(), source=src
        )
        cert.verify_to_height(256)
        assert cert.last_height == 256
        assert cert.last_walk_rounds == 1  # ONE batched launch
        assert cert.last_walk_verifies <= 36  # "dozens", not 256 * 4

    def test_rotating_chain_bisects(self):
        """Heights 1..4 rotate one validator each (the inquirer test's
        chain): a 1->4 jump changes 3 of 4 — must bridge via 2 and 3."""
        sets = {
            1: _privs([1, 2, 3, 4]),
            2: _privs([1, 2, 3, 5]),
            3: _privs([1, 2, 5, 6]),
            4: _privs([1, 5, 6, 7]),
        }
        src, fcs = _chain_source(sets, lambda h: sets[h])
        trusted = MemProvider()
        cert = BisectingCertifier(CHAIN, seed=fcs[1], trusted=trusted, source=src)
        cert.certify(fcs[4])
        assert cert.last_height == 4
        # intermediate hops became trusted (the memoization)
        assert trusted.get_by_height(3).height() >= 2

    def test_dense_rotation_long_chain(self):
        """64 heights rotating one of 8 validators every 4 heights:
        bisection must converge in far fewer verifies than the
        sequential walk's one-commit-per-height."""
        base = list(range(1, 9))

        def privs_for(h):
            rotated = (h - 1) // 4  # rotations accumulated by height h
            ids = base[rotated % 8:] + [100 + i for i in range(rotated)]
            return _privs(sorted(ids[-8:]))

        heights = list(range(1, 65))
        src, fcs = _chain_source(heights, privs_for)
        cert = BisectingCertifier(
            CHAIN, seed=fcs[1], trusted=MemProvider(), source=src
        )
        cert.verify_to_height(64)
        assert cert.last_height == 64
        sequential_verifies = 64 * 8
        assert cert.last_walk_verifies < sequential_verifies / 2
        # the on-device cost term is LAUNCHES (rounds), not rows: the
        # sequential walk pays one per height, bisection a handful total
        assert cert.last_walk_rounds <= 8

    def test_unbridgeable_gap_raises_too_much_change(self):
        sets = {
            1: _privs([1, 2, 3, 4]),
            4: _privs([1, 5, 6, 7]),
        }
        src, fcs = _chain_source(sets, lambda h: sets[h])
        cert = BisectingCertifier(
            CHAIN, seed=fcs[1], trusted=MemProvider(), source=src
        )
        with pytest.raises(ErrTooMuchChange):
            cert.certify(fcs[4])

    def test_trust_period_boundary(self):
        """An expired trusted state must refuse to walk (the skip
        rule's slashing backstop is gone); a fresh one proceeds."""
        privs = _privs(range(1, 5))
        src, fcs = _chain_source((1, 10), lambda h: privs)
        # header times are h * 1e9 ns (test fixture); trust 1 hour
        period_ns = int(3600 * 1e9)
        expired_now = fcs[1].header.time + period_ns + 1
        cert = BisectingCertifier(
            CHAIN,
            seed=fcs[1],
            trusted=MemProvider(),
            source=src,
            trust_period_ns=period_ns,
            now_ns=lambda: expired_now,
        )
        with pytest.raises(ValidationError, match="trust expired"):
            cert.verify_to_height(10)
        fresh = BisectingCertifier(
            CHAIN,
            seed=fcs[1],
            trusted=MemProvider(),
            source=src,
            trust_period_ns=period_ns,
            now_ns=lambda: fcs[1].header.time + period_ns - 1,
        )
        fresh.verify_to_height(10)
        assert fresh.last_height == 10

    def test_one_third_overlap_boundary(self):
        """The skip rule is STRICTLY more than 1/3 of trusted power:
        exactly 1/3 overlap cannot jump, just above it can."""
        old = _privs(range(1, 10))  # 9 validators, power 10 each
        exactly_third = _privs([1, 2, 3] + list(range(20, 26)))  # keep 3/9
        just_above = _privs([1, 2, 3, 4] + list(range(20, 25)))  # keep 4/9
        for new, ok in ((exactly_third, False), (just_above, True)):
            src = MemProvider()
            seed = _full_commit(1, old)
            src.store_commit(seed)
            src.store_commit(_full_commit(2, new))
            cert = BisectingCertifier(
                CHAIN, seed=seed, trusted=MemProvider(), source=src
            )
            if ok:
                cert.verify_to_height(2)
                assert cert.last_height == 2
            else:
                with pytest.raises(ErrTooMuchChange):
                    cert.verify_to_height(2)

    def test_address_reuse_with_attacker_keys_cannot_hijack(self):
        """The trust-hijack regression: a candidate valset reusing
        every TRUSTED address but binding attacker pubkeys, fully
        signed by the attacker keys, passes its own >2/3 quorum by
        construction — it must earn ZERO old-set credit (the trusted
        validator's KEY doesn't match the key the lane signature was
        verified under), never the >1/3 overlap that would pin the
        client to the forged chain."""
        from tendermint_tpu.certifiers.certifier import FullCommit
        from tendermint_tpu.types import Validator, ValidatorSet
        from tendermint_tpu.types.block import Commit, Header
        from tendermint_tpu.types.block_id import BlockID
        from tendermint_tpu.types.part_set import PartSetHeader
        from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT, Vote

        trusted_privs = _privs(range(1, 5))
        seed = _full_commit(1, trusted_privs)
        attackers = _privs(range(11, 15))
        forged_vs = ValidatorSet(
            [
                Validator(
                    address=v.address,
                    pub_key=att.pub_key,
                    voting_power=v.voting_power,
                )
                for v, att in zip(seed.validators.validators, attackers)
            ]
        )
        by_pub = {a.pub_key.data: a for a in attackers}
        header = Header(
            chain_id=CHAIN,
            height=10,
            time=10_000_000_000,
            num_txs=0,
            last_block_id=BlockID.zero(),
            last_commit_hash=b"",
            data_hash=b"",
            validators_hash=forged_vs.hash(),
            app_hash=b"evil",
        )
        bid = BlockID(
            header.hash(), PartSetHeader(total=1, hash=header.hash()[:20])
        )
        precommits = []
        for idx, val in enumerate(forged_vs.validators):
            vote = Vote(
                validator_address=val.address,
                validator_index=idx,
                height=10,
                round=0,
                timestamp=idx + 1,
                type=VOTE_TYPE_PRECOMMIT,
                block_id=bid,
            )
            signer = by_pub[val.pub_key.data]._signer
            precommits.append(
                vote.with_signature(signer.sign(vote.sign_bytes(CHAIN)))
            )
        forged = FullCommit(
            header=header,
            commit=Commit(block_id=bid, precommits=precommits),
            validators=forged_vs,
        )
        src = MemProvider()
        src.store_commit(seed)
        src.store_commit(forged)
        trusted = MemProvider()
        cert = BisectingCertifier(CHAIN, seed=seed, trusted=trusted, source=src)
        with pytest.raises(ErrTooMuchChange):
            cert.verify_to_height(10)
        assert cert.last_height == 1  # trust never moved
        assert trusted.latest_commit().height() == 1

    def test_environmental_failures_are_typed_not_forged(self):
        """Trust expiry and fetch failure are client-side conditions:
        typed errors, separate metric labels — the forgery signal
        operators alert on must not move."""
        from tendermint_tpu.telemetry import REGISTRY

        def forged_count():
            return REGISTRY.counter_value(
                "tendermint_lightclient_bisections_total", result="forged"
            )

        privs = _privs(range(1, 5))
        src, fcs = _chain_source((1, 10), lambda h: privs)
        base = forged_count()
        # empty source: ErrNoSourceCommit, result="no_source"
        cert = BisectingCertifier(
            CHAIN, seed=fcs[1], trusted=MemProvider(), source=MemProvider()
        )
        ns_base = REGISTRY.counter_value(
            "tendermint_lightclient_bisections_total", result="no_source"
        )
        with pytest.raises(ErrNoSourceCommit):
            cert.verify_to_height(10)
        assert (
            REGISTRY.counter_value(
                "tendermint_lightclient_bisections_total", result="no_source"
            )
            == ns_base + 1
        )
        # expired pin: ErrTrustExpired, result="trust_expired"
        period_ns = int(3600 * 1e9)
        expired = BisectingCertifier(
            CHAIN,
            seed=fcs[1],
            trusted=MemProvider(),
            source=src,
            trust_period_ns=period_ns,
            now_ns=lambda: fcs[1].header.time + period_ns + 1,
        )
        te_base = REGISTRY.counter_value(
            "tendermint_lightclient_bisections_total", result="trust_expired"
        )
        with pytest.raises(ErrTrustExpired):
            expired.verify_to_height(10)
        # the direct same-valset certify path is trust-gated too
        with pytest.raises(ErrTrustExpired):
            expired.certify(fcs[10])
        assert (
            REGISTRY.counter_value(
                "tendermint_lightclient_bisections_total", result="trust_expired"
            )
            == te_base + 1
        )
        assert forged_count() == base  # the forgery signal never moved

    def test_forged_signature_is_hard_failure_and_never_cached(self):
        privs = _privs(range(1, 5))
        src, fcs = _chain_source((1, 10), lambda h: privs)
        bad = fcs[10].commit.precommits[1]
        sig = bytearray(bad.signature)
        sig[5] ^= 1
        fcs[10].commit.precommits[1] = bad.with_signature(bytes(sig))
        trusted = MemProvider()
        cert = BisectingCertifier(CHAIN, seed=fcs[1], trusted=trusted, source=src)
        with pytest.raises(ValidationError, match="forged|invalid"):
            cert.verify_to_height(10)
        assert trusted.latest_commit().height() == 1  # forgery never stored

    def test_quorumless_candidate_is_forged(self):
        """A commit that cannot certify its own header (single signer)
        is a provider lie, not a bisection trigger."""
        from tendermint_tpu.types.block import Commit

        privs = _privs(range(1, 5))
        seed = _full_commit(1, privs)  # sign ascending: HRS guard
        fc = _full_commit(10, privs)
        keep = next(
            i for i, p in enumerate(fc.commit.precommits) if p is not None
        )
        fc.commit = Commit(
            block_id=fc.commit.block_id,
            precommits=[
                p if i == keep else None
                for i, p in enumerate(fc.commit.precommits)
            ],
        )
        src = MemProvider()
        src.store_commit(seed)
        src.store_commit(fc)
        cert = BisectingCertifier(CHAIN, seed=seed, trusted=MemProvider(), source=src)
        with pytest.raises(ValidationError, match="quorum"):
            cert.verify_to_height(10)

    def test_trusted_cache_memoizes_walks(self):
        """A second certifier sharing the trusted store restarts at the
        proven height: zero verifies to re-reach it."""
        privs = _privs(range(1, 5))
        src, fcs = _chain_source((1, 256), lambda h: privs)
        db = MemDB()
        cache = CertifiedCommitCache(store=FullCommitStore(db))
        cert = BisectingCertifier(CHAIN, seed=fcs[1], trusted=cache, source=src)
        cert.verify_to_height(256)
        assert cert.last_height == 256
        # fresh certifier, same durable trust, EMPTY source
        cert2 = BisectingCertifier(
            CHAIN,
            seed=fcs[1],
            trusted=CertifiedCommitCache(store=FullCommitStore(db)),
            source=MemProvider(),
        )
        cert2.verify_to_height(256)
        assert cert2.last_height == 256
        assert cert2.last_walk_verifies == 0


class TestBatchedLaunches:
    def test_one_coalesced_launch_per_bisection_round(self):
        """The launch-ledger assertion: every bisection round's commit
        verifies merge into ONE coalesced launch tagged
        consumer=lightclient — never one launch per probed height."""
        from tendermint_tpu.services.batcher import CoalescingVerifier
        from tendermint_tpu.services.verifier import HostBatchVerifier
        from tendermint_tpu.telemetry.launchlog import LAUNCHLOG

        sets = {
            1: _privs([1, 2, 3, 4]),
            2: _privs([1, 2, 3, 5]),
            3: _privs([1, 2, 5, 6]),
            4: _privs([1, 5, 6, 7]),
        }
        src, fcs = _chain_source(sets, lambda h: sets[h])
        verifier = CoalescingVerifier(HostBatchVerifier(), cache_size=0)
        LAUNCHLOG.clear()  # process-global forensics ring: fresh window
        try:
            cert = BisectingCertifier(
                CHAIN,
                seed=fcs[1],
                trusted=MemProvider(),
                source=src,
                verifier=verifier,
            )
            cert.verify_to_height(4)
        finally:
            verifier.close()
        rounds = cert.last_walk_rounds
        assert rounds >= 2  # the rotation forced at least one bisection
        lc_records = [
            r
            for r in LAUNCHLOG.recent()
            if "lightclient" in (r.get("consumers") or {})
        ]
        assert len(lc_records) == rounds, (
            f"expected one coalesced launch per round ({rounds}), "
            f"saw {len(lc_records)}"
        )
        for rec in lc_records:
            assert set(rec["consumers"]) == {"lightclient"}


class TestEvidenceExtraction:
    def _pair(self, double_signer_idx=0):
        from tendermint_tpu.testing.byzantine import forge_fullcommit

        honest = _full_commit(5, _privs(range(1, 5)))
        forged = forge_fullcommit(
            honest, self._ordered(honest)[double_signer_idx], CHAIN
        )
        return honest, forged

    @staticmethod
    def _ordered(fc):
        privs = _privs(range(1, 5))
        by_addr = {p.address: p for p in privs}
        return [by_addr[v.address] for v in fc.validators.validators]

    def test_double_sign_becomes_evidence(self):
        honest, forged = self._pair()
        evs = extract_double_sign_evidence(forged, honest, CHAIN)
        assert len(evs) == 1
        ev = evs[0]
        ev.verify(CHAIN, honest.validators)  # genuine, chain-committable
        assert ev.height == 5

    def test_garbage_signature_yields_nothing(self):
        """A forged precommit with a junk sig is peer noise — it must
        never convict the validator it names."""
        honest, forged = self._pair()
        for i, pc in enumerate(forged.commit.precommits):
            if pc is not None:
                forged.commit.precommits[i] = pc.with_signature(b"\x01" * 64)
        assert extract_double_sign_evidence(forged, honest, CHAIN) == []

    def test_different_round_cannot_pair(self):
        honest, forged = self._pair()
        from dataclasses import replace

        for i, pc in enumerate(forged.commit.precommits):
            if pc is not None:
                forged.commit.precommits[i] = replace(pc, round=1)
        assert extract_double_sign_evidence(forged, honest, CHAIN) == []

    def test_same_block_is_no_conflict(self):
        honest = _full_commit(5, _privs(range(1, 5)))
        assert extract_double_sign_evidence(honest, honest, CHAIN) == []

    def test_height_mismatch_yields_nothing(self):
        honest = _full_commit(5, _privs(range(1, 5)))
        other = _full_commit(6, _privs(range(1, 5)))
        assert extract_double_sign_evidence(other, honest, CHAIN) == []


class TestReactorRoundTrip:
    def _wired_pair(self, serve_cache, client_subscribes=False, certifier=None):
        from tendermint_tpu.lightclient.reactor import LightClientReactor
        from tendermint_tpu.p2p.peer import NodeInfo
        from tendermint_tpu.p2p.switch import Switch, connect_switches

        server = LightClientReactor(chain_id=CHAIN, cache=serve_cache)
        client = LightClientReactor(
            chain_id=CHAIN, subscribe=client_subscribes, certifier=certifier,
            cache=CertifiedCommitCache(),
        )
        sws = []
        for name, reactor in (("server", server), ("client", client)):
            sw = Switch(
                NodeInfo(node_id=f"lc-{name}", moniker=name, chain_id=CHAIN)
            )
            sw.add_reactor("lightclient", reactor)
            sw.start()
            sws.append(sw)
        connect_switches(sws[0], sws[1])
        return server, client, sws

    def test_request_response_serves_certified_cache(self):
        cache = CertifiedCommitCache()
        privs = _privs(range(1, 5))
        cache.put_certified(_full_commit(3, privs))
        cache.put_certified(_full_commit(7, privs))
        server, client, sws = self._wired_pair(cache)
        try:
            fc = client.request_commit(7)
            assert fc is not None and fc.height() == 7
            # floor fallback for a between-heights ask
            fc5 = client.request_commit(5)
            assert fc5 is not None and fc5.height() == 3
            # tip ask
            tip = client.request_commit(0)
            assert tip is not None and tip.height() == 7
        finally:
            for sw in sws:
                sw.stop()

    def test_concurrent_same_height_requests_all_served(self):
        """Wait slots are per-request, not per-height: concurrent
        fetches of the same height must each get the response instead
        of clobbering a shared slot and orphaning each other."""
        cache = CertifiedCommitCache()
        privs = _privs(range(1, 5))
        cache.put_certified(_full_commit(7, privs))
        server, client, sws = self._wired_pair(cache)
        try:
            results = []
            lock = threading.Lock()

            def fetch():
                fc = client.request_commit(7)
                with lock:
                    results.append(fc)

            threads = [threading.Thread(target=fetch) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert len(results) == 4
            assert all(fc is not None and fc.height() == 7 for fc in results)
            assert client._waits == {}  # every waiter cleaned up
        finally:
            for sw in sws:
                sw.stop()

    def test_environmental_push_failure_does_not_score_peer(self):
        """An honest peer pushing the tip while the CLIENT's pin is
        expired (or a bisection fetch times out) must not be banned —
        only genuine forgeries route to misbehavior."""
        from tendermint_tpu.lightclient.reactor import LightClientReactor

        privs = _privs(range(1, 5))
        seed = _full_commit(1, privs)
        period_ns = int(3600 * 1e9)
        expired_cert = BisectingCertifier(
            CHAIN,
            seed=seed,
            trusted=CertifiedCommitCache(),
            source=MemProvider(),
            trust_period_ns=period_ns,
            now_ns=lambda: seed.header.time + period_ns + 1,
        )
        reactor = LightClientReactor(
            chain_id=CHAIN,
            subscribe=True,
            certifier=expired_cert,
            cache=CertifiedCommitCache(),
        )

        class _SwitchStub:
            def __init__(self):
                self.reports = []

            def report_misbehavior(self, peer_id, kind, detail=None):
                self.reports.append((peer_id, kind))

            def peers(self):
                return []

        stub = _SwitchStub()
        reactor.switch = stub
        # a perfectly honest tip push at a new height (valset changed
        # only in the sense that trust can't walk there: expired pin)
        reactor._on_push("honest-peer", _full_commit(5, _privs(range(1, 6))))
        assert stub.reports == []  # no ban, no debit
        assert reactor.cache.get_exact(5) is None  # and nothing cached

    def test_push_certifies_then_forwards(self):
        """A pushed FullCommit is certified through the client's pin
        before caching; the proven tip then fans on to the client's own
        subscribers (replica chains)."""
        privs = _privs(range(1, 5))
        seed = _full_commit(1, privs)
        serve_cache = CertifiedCommitCache()
        serve_cache.put_certified(seed)
        certifier = BisectingCertifier(
            CHAIN, seed=seed, trusted=CertifiedCommitCache(), source=None
        )
        server, client, sws = self._wired_pair(
            serve_cache, client_subscribes=True, certifier=certifier
        )
        try:
            fc5 = _full_commit(5, privs)
            server.cache.put_certified(fc5)
            server.announce(fc5)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if client.cache.get_exact(5) is not None:
                    break
                time.sleep(0.02)
            assert client.cache.get_exact(5) is not None
            stats = client.serving_stats()
            assert stats["last_push_age_s"] is not None
        finally:
            for sw in sws:
                sw.stop()

    def test_forged_push_scores_peer_and_extracts_evidence(self):
        from tendermint_tpu.evidence import EvidencePool
        from tendermint_tpu.testing.byzantine import forge_fullcommit
        from tendermint_tpu.telemetry import REGISTRY

        privs = _privs(range(1, 5))
        seed = _full_commit(1, privs)
        honest5 = _full_commit(5, privs)
        client_cache = CertifiedCommitCache()
        pool = EvidencePool(chain_id=CHAIN)
        certifier = BisectingCertifier(
            CHAIN, seed=seed, trusted=client_cache, source=None
        )
        server, client, sws = self._wired_pair(
            CertifiedCommitCache(), client_subscribes=True, certifier=certifier
        )
        client.evidence_pool = pool
        try:
            # client already trusts the honest height 5
            client.cache.put_certified(honest5)
            certifier.certify(honest5)
            by_addr = {p.address: p for p in privs}
            compromised = by_addr[honest5.validators.validators[0].address]
            forged = forge_fullcommit(honest5, compromised, CHAIN)
            base = REGISTRY.counter_value(
                "tendermint_p2p_peer_misbehavior_total", kind="forged_fullcommit"
            )
            # push the forgery from the SERVER switch's peer object
            from tendermint_tpu.lightclient.reactor import (
                LIGHTCLIENT_CHANNEL,
                _enc_fc_announce,
            )

            peer = sws[0].peers()[0]
            peer.try_send(LIGHTCLIENT_CHANNEL, _enc_fc_announce(forged))
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and pool.depth() == 0:
                time.sleep(0.02)
            assert pool.depth() == 1, "double-sign evidence not extracted"
            ev = pool.pending_evidence()[0]
            assert ev.address == compromised.address
            delta = (
                REGISTRY.counter_value(
                    "tendermint_p2p_peer_misbehavior_total",
                    kind="forged_fullcommit",
                )
                - base
            )
            assert delta >= 1
            # weight 100 = instant ban of the serving peer
            assert sws[1].scorer.is_banned("lc-server")
            # the forgery never entered the certified cache
            assert client.cache.get_exact(5).header.app_hash == honest5.header.app_hash
        finally:
            pool.close()
            for sw in sws:
                sw.stop()


class TestReplicaAcceptance:
    """Live 4-validator + 2-replica net: replicas bootstrap, follow via
    fast-sync tail + FullCommit subscription, serve proofs over p2p and
    RPC, and a light client walks against a REPLICA (not a validator)."""

    def test_replicas_follow_and_serve(self, tmp_path):
        import json
        import urllib.request

        from tendermint_tpu.certifiers.certifier import FullCommit
        from tendermint_tpu.certifiers.node_provider import NodeProvider
        from tendermint_tpu.rpc.client import HTTPClient
        from tendermint_tpu.testing.nemesis import FullNemesisNode, Nemesis

        def replica_mutator(cfg):
            cfg.replica.enable = True

        net = Nemesis(
            4, home=str(tmp_path), node_factory=Nemesis.full_node_factory()
        )
        with net:
            net.wait_height(2, timeout=90)
            reps = []
            for i in (4, 5):
                rep = FullNemesisNode(
                    i,
                    net.genesis,
                    net.privs,
                    str(tmp_path),
                    net.chain_id,
                    config_mutator=replica_mutator,
                )
                net.add_node(rep)
                reps.append(rep)
            # replicas follow the chain without joining consensus
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline and not all(
                r.height >= 3 for r in reps
            ):
                time.sleep(0.1)
            assert all(r.height >= 3 for r in reps), [r.height for r in reps]
            assert all(r.node.consensus is None for r in reps)
            # subscription stream certified the tip into the cache
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and not all(
                r.node.fullcommit_cache.latest_height() >= 3 for r in reps
            ):
                time.sleep(0.1)
            assert all(
                r.node.fullcommit_cache.latest_height() >= 3 for r in reps
            )
            rep = reps[0]
            # health: ready, follow-mode sync check, serving section
            h = rep.node.health()
            assert h["status"] in ("ok", "degraded")
            assert h["checks"]["sync"]["follow"] is True
            assert h["serving"]["replica"] is True
            assert h["serving"]["serving_lag"] is not None
            assert h["serving"]["last_push_age_s"] is not None
            # RPC full_commit route serves a decodable proof unit
            url = f"http://127.0.0.1:{rep.rpc_port}/full_commit?height=2"
            with urllib.request.urlopen(url, timeout=10) as resp:
                out = json.load(resp)["result"]
            fc = FullCommit.decode(bytes.fromhex(out["full_commit"]))
            assert fc.height() == 2
            # a light client walks against the REPLICA fleet
            client_cert = BisectingCertifier(
                net.chain_id,
                validators=net.genesis.validator_set(),
                height=0,
                trusted=CertifiedCommitCache(),
                source=NodeProvider(HTTPClient(f"127.0.0.1:{rep.rpc_port}")),
            )
            target = rep.height
            client_cert.verify_to_height(target)
            assert client_cert.last_height >= 2
            assert client_cert.last_walk_rounds <= 3  # skipping, not walking
