import pytest

from tendermint_tpu.crypto import PrivKey
from tendermint_tpu.types import (
    VOTE_TYPE_PRECOMMIT,
    VOTE_TYPE_PREVOTE,
    ErrDoubleSign,
    PrivValidator,
    PrivValidatorFS,
    Vote,
)
from tests.helpers import CHAIN_ID, make_block_id


def mk_vote(pv, height, round_, type_, bid, ts=1000):
    return Vote(
        validator_address=pv.address,
        validator_index=0,
        height=height,
        round=round_,
        timestamp=ts,
        type=type_,
        block_id=bid,
    )


def test_sign_vote_and_verify():
    pv = PrivValidator(PrivKey(b"\x05" * 32))
    v = pv.sign_vote(CHAIN_ID, mk_vote(pv, 1, 0, VOTE_TYPE_PREVOTE, make_block_id()))
    assert pv.pub_key.verify(v.sign_bytes(CHAIN_ID), v.signature)


def test_double_sign_same_hrs_different_block_refused():
    pv = PrivValidator(PrivKey(b"\x05" * 32))
    pv.sign_vote(CHAIN_ID, mk_vote(pv, 1, 0, VOTE_TYPE_PREVOTE, make_block_id(b"a")))
    with pytest.raises(ErrDoubleSign):
        pv.sign_vote(CHAIN_ID, mk_vote(pv, 1, 0, VOTE_TYPE_PREVOTE, make_block_id(b"b")))


def test_resign_identical_returns_cached():
    pv = PrivValidator(PrivKey(b"\x05" * 32))
    v1 = pv.sign_vote(CHAIN_ID, mk_vote(pv, 1, 0, VOTE_TYPE_PREVOTE, make_block_id()))
    v2 = pv.sign_vote(CHAIN_ID, mk_vote(pv, 1, 0, VOTE_TYPE_PREVOTE, make_block_id()))
    assert v1.signature == v2.signature


def test_regression_refused():
    pv = PrivValidator(PrivKey(b"\x05" * 32))
    pv.sign_vote(CHAIN_ID, mk_vote(pv, 2, 0, VOTE_TYPE_PRECOMMIT, make_block_id()))
    with pytest.raises(ErrDoubleSign):
        pv.sign_vote(CHAIN_ID, mk_vote(pv, 1, 0, VOTE_TYPE_PREVOTE, make_block_id()))
    # prevote after precommit at same height/round is also a step regression
    with pytest.raises(ErrDoubleSign):
        pv.sign_vote(CHAIN_ID, mk_vote(pv, 2, 0, VOTE_TYPE_PREVOTE, make_block_id()))


def test_step_progression_allowed():
    pv = PrivValidator(PrivKey(b"\x05" * 32))
    bid = make_block_id()
    pv.sign_vote(CHAIN_ID, mk_vote(pv, 1, 0, VOTE_TYPE_PREVOTE, bid))
    pv.sign_vote(CHAIN_ID, mk_vote(pv, 1, 0, VOTE_TYPE_PRECOMMIT, bid))
    pv.sign_vote(CHAIN_ID, mk_vote(pv, 1, 1, VOTE_TYPE_PREVOTE, bid))
    pv.sign_vote(CHAIN_ID, mk_vote(pv, 2, 0, VOTE_TYPE_PREVOTE, bid))


def test_fs_persistence_survives_reload(tmp_path):
    path = str(tmp_path / "priv_validator.json")
    pv = PrivValidatorFS.load_or_gen(path, seed=b"\x09" * 32)
    pv.sign_vote(CHAIN_ID, mk_vote(pv, 3, 0, VOTE_TYPE_PRECOMMIT, make_block_id()))

    pv2 = PrivValidatorFS.load(path)
    assert pv2.address == pv.address
    # double sign attempt after restart is still refused
    with pytest.raises(ErrDoubleSign):
        pv2.sign_vote(CHAIN_ID, mk_vote(pv2, 3, 0, VOTE_TYPE_PREVOTE, make_block_id()))
    # progress is fine
    pv2.sign_vote(CHAIN_ID, mk_vote(pv2, 4, 0, VOTE_TYPE_PREVOTE, make_block_id()))


def test_load_or_gen_idempotent(tmp_path):
    path = str(tmp_path / "pv.json")
    a = PrivValidatorFS.load_or_gen(path)
    b = PrivValidatorFS.load_or_gen(path)
    assert a.address == b.address


def test_resign_differing_only_by_timestamp_reuses_cached_vote():
    # crash-replay: the restarted node rebuilds the same vote with a
    # fresh clock — must get the ORIGINAL timestamp+signature back, not
    # an ErrDoubleSign wedge (reference checkVotesOnlyDifferByTimestamp)
    pv = PrivValidator(PrivKey(b"\x05" * 32))
    bid = make_block_id()
    v1 = pv.sign_vote(CHAIN_ID, mk_vote(pv, 2, 0, VOTE_TYPE_PRECOMMIT, bid, ts=1000))
    v2 = pv.sign_vote(CHAIN_ID, mk_vote(pv, 2, 0, VOTE_TYPE_PRECOMMIT, bid, ts=9999))
    assert v2.timestamp == 1000  # cached artifact, not a new signature
    assert v2.signature == v1.signature
    assert pv.pub_key.verify(v2.sign_bytes(CHAIN_ID), v2.signature)
    # a DIFFERENT block at the same HRS is still refused
    with pytest.raises(ErrDoubleSign):
        pv.sign_vote(
            CHAIN_ID, mk_vote(pv, 2, 0, VOTE_TYPE_PRECOMMIT, make_block_id(b"other"))
        )
