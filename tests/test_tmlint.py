"""tmlint engine self-tests: per-rule fixture corpus (good files stay
clean, bad files produce exactly the expected findings), suppression
semantics (reasoned suppressions hide, reasonless ones are S001),
baseline add/remove semantics, CLI exit codes, and the --changed mode's
file selection. All marked `lint` (pytest.ini) so the engine's own
coverage is selectable with -m lint while staying in tier-1."""

import json
import pathlib
import shutil
import subprocess
import sys

import pytest

from tendermint_tpu.analysis import engine

pytestmark = pytest.mark.lint

REPO = engine.repo_root()
FIXTURES = REPO / "tendermint_tpu" / "analysis" / "fixtures"


def lint_fixture(name: str, rules: list[str]) -> list[engine.Finding]:
    report = engine.lint_paths([FIXTURES / name], rules=rules)
    return report.findings


class TestRuleFixtures:
    def test_l001_bad_flags_both_sites(self):
        findings = lint_fixture("L001_bad.py", ["L001"])
        assert len(findings) == 2
        assert all(f.rule == "L001" for f in findings)
        assert "mempool.wal" in findings[0].message
        assert "mempool.counter" in findings[0].message

    def test_l001_good_is_clean(self):
        assert lint_fixture("L001_good.py", ["L001"]) == []

    def test_l002_bad_flags_every_blocking_call(self):
        findings = lint_fixture("L002_bad.py", ["L002"])
        msgs = "\n".join(f.message for f in findings)
        assert "time.sleep" in msgs
        assert ".result" in msgs or "result()" in msgs
        assert "join" in msgs
        assert "get" in msgs
        assert "wait" in msgs
        assert len(findings) == 5

    def test_l002_good_is_clean(self):
        assert lint_fixture("L002_good.py", ["L002"]) == []

    def test_t001_bad_flags_bare_and_silent(self):
        findings = lint_fixture("T001_bad.py", ["T001"])
        assert len(findings) == 4  # bare + reactor + run + _recv_loop
        assert any("bare" in f.message for f in findings)

    def test_t001_good_is_clean(self):
        assert lint_fixture("T001_good.py", ["T001"]) == []

    def test_w001_bad_flags_reads_after_tail(self):
        findings = lint_fixture("W001_bad.py", ["W001"])
        assert len(findings) == 2
        assert all("trailing-optional" in f.message for f in findings)

    def test_w001_good_is_clean(self):
        assert lint_fixture("W001_good.py", ["W001"]) == []

    def test_j001_bad_flags_effects_and_branches(self):
        findings = lint_fixture("J001_bad.py", ["J001"])
        msgs = "\n".join(f.message for f in findings)
        assert "print" in msgs
        assert "time.time" in msgs
        assert "branch on traced" in msgs.lower()
        assert len(findings) == 4

    def test_j001_good_is_clean(self):
        assert lint_fixture("J001_good.py", ["J001"]) == []

    def test_m001_bad_flags_only_the_unregistered_name(self):
        findings = lint_fixture("M001_bad.py", ["M001"])
        assert len(findings) == 1
        assert "tendermint_not_in_the_catalog_total" in findings[0].message

    def test_m002_bad_flags_only_the_uncataloged_span(self):
        findings = lint_fixture("M002_bad.py", ["M002"])
        assert len(findings) == 1
        assert "not.in.catalog" in findings[0].message

    def test_m003_bad_flags_kernel_without_slow(self, tmp_path):
        # M003 scopes to test files: alias the fixture into one
        target = tmp_path / "test_m003_fixture.py"
        shutil.copy(FIXTURES / "M003_bad.py", target)
        report = engine.lint_paths([target], rules=["M003"])
        names = "\n".join(f.message for f in report.findings)
        assert len(report.findings) == 2
        assert "test_compiles_kernel_only" in names
        assert "test_inherits_kernel_only" in names  # class-level mark
        assert "test_compiles_both_marks" not in names

    def test_s001_reasonless_suppression_is_a_finding(self):
        report = engine.lint_paths([FIXTURES / "S001_bad.py"])
        s001 = [f for f in report.findings if f.rule == "S001"]
        assert len(s001) == 1
        # the reasoned suppression hid its L002; the reasonless one did NOT
        l002 = [f for f in report.findings if f.rule == "L002"]
        assert len(l002) == 1
        assert len(report.suppressed) == 1


class TestSuppressions:
    def test_suppression_on_line_above_applies(self, tmp_path):
        mod = tmp_path / "mod.py"
        mod.write_text(
            "import time\n"
            "from tendermint_tpu.utils.lockrank import ranked_lock\n"
            "_lock = ranked_lock('dispatch.state')\n"
            "def f():\n"
            "    with _lock:\n"
            "        # tmlint: disable=L002 -- test: line-above placement\n"
            "        time.sleep(0.1)\n"
        )
        report = engine.lint_paths([mod], rules=["L002", "S001"])
        assert report.findings == []
        assert len(report.suppressed) == 1

    def test_suppression_only_hides_named_rule(self, tmp_path):
        mod = tmp_path / "mod.py"
        mod.write_text(
            "import time\n"
            "from tendermint_tpu.utils.lockrank import ranked_lock\n"
            "_lock = ranked_lock('dispatch.state')\n"
            "def f():\n"
            "    with _lock:\n"
            "        time.sleep(0.1)  # tmlint: disable=T001 -- test: wrong rule named\n"
        )
        report = engine.lint_paths([mod], rules=["L002", "S001"])
        assert [f.rule for f in report.findings] == ["L002"]


class TestBaseline:
    def _bad_module(self, tmp_path) -> pathlib.Path:
        mod = tmp_path / "mod.py"
        mod.write_text(
            "import time\n"
            "from tendermint_tpu.utils.lockrank import ranked_lock\n"
            "_lock = ranked_lock('dispatch.state')\n"
            "def f():\n"
            "    with _lock:\n"
            "        time.sleep(0.1)\n"
        )
        return mod

    def test_baseline_grandfathers_then_goes_stale(self, tmp_path):
        mod = self._bad_module(tmp_path)
        baseline = tmp_path / "baseline.json"
        report = engine.lint_paths([mod], rules=["L002"])
        assert len(report.findings) == 1
        engine.write_baseline(baseline, report.findings)

        # same finding now baselined, not fresh
        report2 = engine.lint_paths([mod], rules=["L002"], baseline_path=baseline)
        assert report2.findings == []
        assert len(report2.baselined) == 1
        assert report2.stale_baseline == []

        # fix the code: the entry is reported stale (prune signal)
        mod.write_text("def f():\n    return 1\n")
        report3 = engine.lint_paths([mod], rules=["L002"], baseline_path=baseline)
        assert report3.findings == []
        assert len(report3.stale_baseline) == 1

    def test_baseline_survives_line_drift(self, tmp_path):
        mod = self._bad_module(tmp_path)
        baseline = tmp_path / "baseline.json"
        engine.write_baseline(
            baseline, engine.lint_paths([mod], rules=["L002"]).findings
        )
        # shift every line down: fingerprints key on source text, not line
        mod.write_text("# a new leading comment\n" + mod.read_text())
        report = engine.lint_paths([mod], rules=["L002"], baseline_path=baseline)
        assert report.findings == []
        assert len(report.baselined) == 1

    def test_new_finding_not_masked_by_baseline(self, tmp_path):
        mod = self._bad_module(tmp_path)
        baseline = tmp_path / "baseline.json"
        engine.write_baseline(
            baseline, engine.lint_paths([mod], rules=["L002"]).findings
        )
        mod.write_text(
            mod.read_text()
            + "def g(q):\n    with _lock:\n        return q.get()\n"
        )
        report = engine.lint_paths([mod], rules=["L002"], baseline_path=baseline)
        assert len(report.findings) == 1  # the NEW .get() only
        assert len(report.baselined) == 1

    def test_repo_baseline_file_is_valid_and_empty(self):
        data = json.loads(
            (REPO / "tools" / "tmlint_baseline.json").read_text()
        )
        assert data["version"] == 1
        assert data["findings"] == {}


def run_cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "tools.tmlint", *args],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )


class TestCLI:
    def test_merged_tree_is_clean_exit_0(self):
        proc = run_cli("tendermint_tpu")
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_bad_fixture_exits_1(self):
        proc = run_cli(
            str(FIXTURES / "L001_bad.py"), "--rules", "L001", "--no-baseline"
        )
        assert proc.returncode == 1
        assert "L001" in proc.stdout

    def test_unknown_rule_exits_2(self):
        proc = run_cli("--rules", "Z999", "tendermint_tpu/analysis")
        assert proc.returncode == 2

    def test_missing_path_exits_2(self):
        proc = run_cli("no/such/dir")
        assert proc.returncode == 2

    def test_list_rules(self):
        proc = run_cli("--list-rules")
        assert proc.returncode == 0
        for code in ("L001", "L002", "T001", "W001", "J001", "M001", "M002",
                     "M003", "S001"):
            assert code in proc.stdout

    def test_changed_mode_lints_a_dirty_file(self, tmp_path):
        # a scratch clone would be heavy; instead verify the plumbing:
        # an untracked bad file inside the repo is picked up, then removed
        scratch = REPO / "tools" / "_tmlint_changed_scratch.py"
        scratch.write_text(
            "import time\n"
            "from tendermint_tpu.utils.lockrank import ranked_lock\n"
            "_lock = ranked_lock('dispatch.state')\n"
            "def f():\n"
            "    with _lock:\n"
            "        time.sleep(0.1)\n"
        )
        try:
            proc = run_cli("--changed", "--no-baseline", "--rules", "L002")
            assert "_tmlint_changed_scratch.py" in proc.stdout
            assert proc.returncode == 1
        finally:
            scratch.unlink()


class TestConftestShims:
    """The re-homed lints keep their conftest API (tests/test_marker_lint.py
    exercises the original signatures; this pins the delegation)."""

    def test_metric_shim_delegates(self, tmp_path):
        from tests.conftest import lint_metric_catalog

        (tmp_path / "mod.py").write_text('N = "tendermint_shim_check_total"\n')
        off = lint_metric_catalog(roots=[tmp_path])
        assert len(off) == 1 and off[0].endswith("tendermint_shim_check_total")

    def test_collection_gate_reports_tmlint_findings(self, monkeypatch):
        import tests.conftest as conftest

        monkeypatch.setattr(
            conftest, "run_tmlint_gate", lambda: "mod.py:1: L001 boom"
        )
        with pytest.raises(pytest.UsageError, match="tmlint"):
            conftest.pytest_collection_modifyitems(None, [])

    def test_repo_gate_is_currently_clean(self):
        from tests.conftest import run_tmlint_gate

        assert run_tmlint_gate() is None
