"""WAL file rotation (reference autofile.Group rolling files)."""

import os

from tendermint_tpu.consensus.wal import WAL, EndHeightMessage, MsgRecord
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE, Vote


def _vote(height):
    return Vote(
        validator_address=b"\x01" * 20,
        validator_index=0,
        height=height,
        round=0,
        timestamp=1000,
        type=VOTE_TYPE_PREVOTE,
        block_id=BlockID(b"\x02" * 32, PartSetHeader(total=1, hash=b"\x03" * 20)),
        signature=b"\x04" * 64,
    )


class TestWALRotation:
    def test_rotates_at_height_boundaries_and_replays_across(self, tmp_path):
        path = str(tmp_path / "cs.wal")
        wal = WAL(path, max_file_bytes=400, max_segments=100)
        for h in range(1, 8):
            wal.save(MsgRecord(_vote(h), "peerX"))
            wal.save(EndHeightMessage(h))
        # in-progress height 8: one vote after the last marker
        wal.save(MsgRecord(_vote(8), "peerX"))
        wal.close()
        segments = WAL.segment_paths(path)
        assert len(segments) > 2, "no rotation happened"
        # every record survives, in order, across segments
        recs = list(WAL.iter_records(path))
        heights = [r.height for r in recs if isinstance(r, EndHeightMessage)]
        assert heights == list(range(1, 8))
        # replay for the in-progress height finds the marker even though
        # it may live in an earlier (rotated) segment
        replay = WAL.records_since_last_end_height(path, height=8)
        assert replay is not None and len(replay) == 1
        assert isinstance(replay[0], MsgRecord) and replay[0].msg.height == 8

    def test_prunes_oldest_segments(self, tmp_path):
        path = str(tmp_path / "cs.wal")
        wal = WAL(path, max_file_bytes=200, max_segments=2)
        for h in range(1, 12):
            wal.save(MsgRecord(_vote(h), "p"))
            wal.save(EndHeightMessage(h))
        wal.close()
        segments = WAL.segment_paths(path)
        assert len(segments) <= 3  # 2 rotated + live
        # recent heights still replayable
        replay = WAL.records_since_last_end_height(path, height=11)
        assert replay is not None

    def test_corrupt_rotated_segment_raises(self, tmp_path):
        # corruption in a NON-tail segment is data loss mid-stream, not
        # a crash tail: replay must fail loudly, not yield a gapped log
        import pytest

        path = str(tmp_path / "cs.wal")
        wal = WAL(path, max_file_bytes=200, max_segments=10)
        for h in range(1, 8):
            wal.save(MsgRecord(_vote(h), "p"))
            wal.save(EndHeightMessage(h))
        wal.close()
        segments = WAL.segment_paths(path)
        assert len(segments) > 2
        victim = segments[0]
        data = bytearray(open(victim, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(victim, "wb").write(bytes(data))
        with pytest.raises(ValueError, match="corrupt WAL segment"):
            list(WAL.iter_records(path))

    def test_cut_wal_until_spans_segments(self, tmp_path, capsys):
        from tendermint_tpu.cmd import main as cli_main

        path = str(tmp_path / "cs.wal")
        wal = WAL(path, max_file_bytes=200, max_segments=100)
        for h in range(1, 10):
            wal.save(MsgRecord(_vote(h), "p"))
            wal.save(EndHeightMessage(h))
        wal.close()
        assert len(WAL.segment_paths(path)) > 2
        out = str(tmp_path / "cut.wal")
        assert cli_main(["cut_wal_until", path, "4", out]) == 0
        heights = [
            r.height for r in WAL.iter_records(out) if isinstance(r, EndHeightMessage)
        ]
        assert heights == [1, 2, 3]  # everything at/after height 4 cut
