"""WAL file rotation (reference autofile.Group rolling files)."""

import os

from tendermint_tpu.consensus.wal import WAL, EndHeightMessage, MsgRecord
from tendermint_tpu.types.block_id import BlockID
from tendermint_tpu.types.part_set import PartSetHeader
from tendermint_tpu.types.vote import VOTE_TYPE_PREVOTE, Vote


def _vote(height):
    return Vote(
        validator_address=b"\x01" * 20,
        validator_index=0,
        height=height,
        round=0,
        timestamp=1000,
        type=VOTE_TYPE_PREVOTE,
        block_id=BlockID(b"\x02" * 32, PartSetHeader(total=1, hash=b"\x03" * 20)),
        signature=b"\x04" * 64,
    )


class TestWALRotation:
    def test_rotates_at_height_boundaries_and_replays_across(self, tmp_path):
        path = str(tmp_path / "cs.wal")
        wal = WAL(path, max_file_bytes=400, max_segments=100)
        for h in range(1, 8):
            wal.save(MsgRecord(_vote(h), "peerX"))
            wal.save(EndHeightMessage(h))
        # in-progress height 8: one vote after the last marker
        wal.save(MsgRecord(_vote(8), "peerX"))
        wal.close()
        segments = WAL.segment_paths(path)
        assert len(segments) > 2, "no rotation happened"
        # every record survives, in order, across segments
        recs = list(WAL.iter_records(path))
        heights = [r.height for r in recs if isinstance(r, EndHeightMessage)]
        assert heights == list(range(1, 8))
        # replay for the in-progress height finds the marker even though
        # it may live in an earlier (rotated) segment
        replay = WAL.records_since_last_end_height(path, height=8)
        assert replay is not None and len(replay) == 1
        assert isinstance(replay[0], MsgRecord) and replay[0].msg.height == 8

    def test_prunes_oldest_segments(self, tmp_path):
        path = str(tmp_path / "cs.wal")
        wal = WAL(path, max_file_bytes=200, max_segments=2)
        for h in range(1, 12):
            wal.save(MsgRecord(_vote(h), "p"))
            wal.save(EndHeightMessage(h))
        wal.close()
        segments = WAL.segment_paths(path)
        assert len(segments) <= 3  # 2 rotated + live
        # recent heights still replayable
        replay = WAL.records_since_last_end_height(path, height=11)
        assert replay is not None
