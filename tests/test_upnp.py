"""UPnP IGD discovery + port mapping against a FAKE gateway
(reference `p2p/upnp` — real gateways don't exist in CI, so the SSDP
responder and SOAP endpoint are local stand-ins)."""

import re
import socket
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

from tendermint_tpu.p2p import upnp

_DESC = """<?xml version="1.0"?>
<root>
  <device>
    <serviceList>
      <service>
        <serviceType>urn:schemas-upnp-org:service:WANIPConnection:1</serviceType>
        <controlURL>/ctl</controlURL>
      </service>
    </serviceList>
  </device>
</root>"""


class FakeGateway:
    """UDP SSDP responder + HTTP description/SOAP endpoint."""

    def __init__(self):
        self.mappings = {}
        self.requests = []

        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _respond(self, body: str):
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._respond(_DESC)

            def do_POST(self):
                body = self.rfile.read(
                    int(self.headers.get("Content-Length", 0))
                ).decode()
                action = self.headers.get("SOAPAction", "")
                fake.requests.append(action)
                if "GetExternalIPAddress" in action:
                    self._respond(
                        "<NewExternalIPAddress>203.0.113.7</NewExternalIPAddress>"
                    )
                elif "AddPortMapping" in action:
                    port = re.search(
                        r"<NewExternalPort>(\d+)</NewExternalPort>", body
                    ).group(1)
                    client = re.search(
                        r"<NewInternalClient>([^<]*)</NewInternalClient>", body
                    ).group(1)
                    fake.mappings[int(port)] = client
                    self._respond("<ok/>")
                elif "DeletePortMapping" in action:
                    port = re.search(
                        r"<NewExternalPort>(\d+)</NewExternalPort>", body
                    ).group(1)
                    fake.mappings.pop(int(port), None)
                    self._respond("<ok/>")
                else:
                    self.send_error(500)

        self.httpd = HTTPServer(("127.0.0.1", 0), Handler)
        self.http_port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

        # SSDP over localhost UDP (unicast stand-in for the multicast)
        self.udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.udp.bind(("127.0.0.1", 0))
        self.ssdp_addr = self.udp.getsockname()

        def ssdp_loop():
            while True:
                try:
                    data, src = self.udp.recvfrom(2048)
                except OSError:
                    return
                if b"M-SEARCH" in data:
                    resp = (
                        "HTTP/1.1 200 OK\r\n"
                        f"LOCATION: http://127.0.0.1:{self.http_port}/desc.xml\r\n"
                        "ST: urn:schemas-upnp-org:device:InternetGatewayDevice:1\r\n\r\n"
                    )
                    self.udp.sendto(resp.encode(), src)

        threading.Thread(target=ssdp_loop, daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.udp.close()


class TestUPnP:
    def test_probe_maps_and_cleans_up(self):
        gw = FakeGateway()
        try:
            result = upnp.probe(port=46700, ssdp_addr=gw.ssdp_addr)
            assert result["external_ip"] == "203.0.113.7"
            assert result["port"] == 46700
            # mapping was created then deleted (probe cleans up)
            assert 46700 not in gw.mappings
            actions = " ".join(gw.requests)
            assert "AddPortMapping" in actions and "DeletePortMapping" in actions
        finally:
            gw.stop()

    def test_add_and_delete_mapping(self):
        gw = FakeGateway()
        try:
            g = upnp.discover(ssdp_addr=gw.ssdp_addr)
            assert g.service_type.endswith("WANIPConnection:1")
            upnp.add_port_mapping(g, 46701, 46656)
            assert gw.mappings.get(46701) == g.local_ip
            upnp.delete_port_mapping(g, 46701)
            assert 46701 not in gw.mappings
        finally:
            gw.stop()

    def test_no_gateway_raises(self):
        import pytest

        with pytest.raises(upnp.UPnPError, match="no UPnP gateway"):
            upnp.discover(timeout=0.3, ssdp_addr=("127.0.0.1", 9))
