"""SecretConnection: authenticated encryption on peer links
(reference `p2p/secret_connection_test.go`)."""

import threading

import pytest

pytest.importorskip(
    "cryptography",
    reason="SecretConnection needs the cryptography package (X25519/AEAD); "
    "there is deliberately NO pure-Python fallback for transport crypto",
)

from tendermint_tpu.crypto.keys import PrivKey
from tendermint_tpu.p2p.secret import HandshakeError, SecretEndpoint
from tendermint_tpu.p2p.transport import EndpointClosed, pipe_pair


def _pair(key_a=None, key_b=None):
    a, b = pipe_pair()
    ka = key_a or PrivKey(b"\x01" * 32)
    kb = key_b or PrivKey(b"\x02" * 32)
    out = {}

    def side_b():
        out["b"] = SecretEndpoint(b, kb)

    t = threading.Thread(target=side_b, daemon=True)
    t.start()
    sa = SecretEndpoint(a, ka)
    t.join(timeout=5)
    return sa, out["b"], ka, kb


class TestSecretConnection:
    def test_round_trip_and_identity(self):
        sa, sb, ka, kb = _pair()
        assert sa.remote_pub_key.data == kb.pub_key.data
        assert sb.remote_pub_key.data == ka.pub_key.data
        sa.send(b"over the wire")
        assert sb.recv(timeout=2) == b"over the wire"
        sb.send(b"and back")
        assert sa.recv(timeout=2) == b"and back"

    def test_many_frames_nonce_progression(self):
        sa, sb, _, _ = _pair()
        for i in range(50):
            sa.send(b"frame-%d" % i)
        for i in range(50):
            assert sb.recv(timeout=2) == b"frame-%d" % i

    def test_tampered_frame_kills_link(self):
        # raw pipe in the middle so we can corrupt ciphertext
        a, mid_a = pipe_pair()
        mid_b, b = pipe_pair()
        done = {}

        def side_b():
            done["b"] = SecretEndpoint(b, PrivKey(b"\x02" * 32))

        t = threading.Thread(target=side_b, daemon=True)
        t.start()

        # relay handshake honestly, then tamper with the next frame
        def relay(n):
            for _ in range(n):
                mid_b.send(mid_a.recv(timeout=5))

        relay_t = threading.Thread(
            target=lambda: relay(2), daemon=True
        )  # eph key + auth frame
        relay_back = threading.Thread(
            target=lambda: [mid_a.send(mid_b.recv(timeout=5)) for _ in range(2)],
            daemon=True,
        )
        relay_t.start()
        relay_back.start()
        sa = SecretEndpoint(a, PrivKey(b"\x01" * 32))
        t.join(timeout=5)
        sb = done["b"]

        sa.send(b"legit")
        frame = bytearray(mid_a.recv(timeout=2))
        frame[0] ^= 0xFF
        mid_b.send(bytes(frame))
        with pytest.raises(EndpointClosed):
            sb.recv(timeout=2)

    def test_mitm_cannot_forge_identity(self):
        # a MITM terminating both handshakes ends up presenting ITS key,
        # not the victim's — identity pinning upstream catches it; here
        # we check the transcript signature itself rejects splicing: a
        # wrong signature in the auth frame fails the handshake
        a, b = pipe_pair()

        def bad_side():
            from cryptography.hazmat.primitives import serialization
            from cryptography.hazmat.primitives.asymmetric.x25519 import (
                X25519PrivateKey,
            )

            eph = X25519PrivateKey.generate()
            b.send(
                eph.public_key().public_bytes(
                    serialization.Encoding.Raw, serialization.PublicFormat.Raw
                )
            )
            b.recv(timeout=5)  # peer eph
            # send garbage instead of a valid encrypted auth frame
            b.send(b"\x00" * 96)

        t = threading.Thread(target=bad_side, daemon=True)
        t.start()
        with pytest.raises((HandshakeError, EndpointClosed)):
            SecretEndpoint(a, PrivKey(b"\x01" * 32))
