"""Fast-sync: BlockPool scheduling + syncing a 200-block store into a
fresh node over the p2p network with window-batched commit verification
(reference `blockchain/pool_test.go`, `blockchain/reactor.go:191-289`;
BASELINE config 3 shape).
"""

import time

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.client import local_client_creator
from tendermint_tpu.blockchain import BlockchainReactor, BlockPool, BlockStore
from tendermint_tpu.db.kv import MemDB
from tendermint_tpu.p2p import NodeInfo, Switch, connect_switches
from tendermint_tpu.state import make_genesis_state

from tests.helpers import CHAIN_ID as CHAIN
from tests.helpers import ChainSim


def wait_until(pred, timeout=60.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


class TestBlockPool:
    def test_schedules_up_to_cap(self):
        pool = BlockPool(start_height=1, max_pending=8)
        pool.set_peer_height("p1", 100)
        pool.set_peer_height("p2", 100)
        reqs, evict = pool.schedule_requests(now=0.0)
        assert len(reqs) == 8 and not evict
        assert {h for _, h in reqs} == set(range(1, 9))
        # both peers get load
        assert {p for p, _ in reqs} == {"p1", "p2"}
        # nothing new while outstanding
        assert pool.schedule_requests(now=1.0) == ([], [])

    def test_timeout_evicts_peer_and_reassigns(self):
        pool = BlockPool(start_height=1, max_pending=4)
        pool.set_peer_height("p1", 100)
        reqs, evict = pool.schedule_requests(now=0.0)
        assert {p for p, _ in reqs} == {"p1"} and not evict
        pool.set_peer_height("p2", 100)
        # p1 never answers: evicted at timeout, heights rescheduled to
        # p2 in the same tick (byzantine defense: a peer advertising an
        # unserved height can no longer pin max_peer_height forever)
        reqs2, evict2 = pool.schedule_requests(now=100.0)
        assert evict2 == ["p1"]
        assert {p for p, _ in reqs2} == {"p2"}
        assert {h for _, h in reqs2} == set(range(1, 5))
        assert pool.num_peers() == 1

    def test_slow_drip_peer_evicted_below_min_recv_rate(self):
        """A peer that keeps responding but below the 10 kB/s floor is
        evicted (reference pool.go:33,121-126) while the healthy peer
        keeps the sync going — a trickle must not throttle the window."""
        import types

        clock = [0.0]
        pool = BlockPool(start_height=1, max_pending=8, time_fn=lambda: clock[0])
        pool.set_peer_height("slow", 100)
        pool.set_peer_height("fast", 100)
        reqs, evict = pool.schedule_requests(now=clock[0])
        assert not evict and {p for p, _ in reqs} == {"slow", "fast"}
        by_peer = {}
        for p, h in reqs:
            by_peer.setdefault(p, []).append(h)

        def blk(h):
            return types.SimpleNamespace(
                header=types.SimpleNamespace(height=h)
            )

        # 5 seconds pass: fast delivers all its blocks at ~40 kB/s,
        # slow drips one tiny response (~20 B/s) — alive, but a trickle
        for t in (1.0, 2.0, 3.0, 4.0, 5.0):
            clock[0] = t
            for h in by_peer["fast"]:
                pool.add_block("fast", blk(h), size=8000)
            by_peer["fast"] = []
        pool.add_block("slow", blk(by_peer["slow"][0]), size=100)

        reqs2, evict2 = pool.schedule_requests(now=clock[0])
        assert evict2 == ["slow"]
        assert pool.num_peers() == 1
        # the freed heights rescheduled to the healthy peer in-tick
        assert reqs2 and {p for p, _ in reqs2} == {"fast"}

    def test_rejects_unrequested_blocks(self):
        import types

        pool = BlockPool(start_height=1)
        pool.set_peer_height("p1", 10)
        pool.schedule_requests(now=0.0)
        fake = types.SimpleNamespace(
            header=types.SimpleNamespace(height=1)
        )
        assert not pool.add_block("stranger", fake)  # wrong peer
        req_peer = pool._requests[1].peer_id
        assert pool.add_block(req_peer, fake)
        assert pool.peek(1) == [fake]

    def test_redo_drops_suffix_and_names_peer(self):
        import types

        pool = BlockPool(start_height=1)
        pool.set_peer_height("p1", 10)
        pool.schedule_requests(now=0.0)
        for h in range(1, 4):
            blk = types.SimpleNamespace(header=types.SimpleNamespace(height=h))
            pool.add_block(pool._requests[h].peer_id, blk)
        assert len(pool.peek(3)) == 3
        bad = pool.redo(2)
        assert bad == "p1"
        assert len(pool.peek(3)) == 1  # height 1 survives


def _pipelined_reactor(sim: ChainSim, depth=2, verifier=None, app=None):
    """A fresh fast-syncing reactor with `sim`'s whole chain pre-loaded
    into its pool (the bench/ordering harness: drive `_try_sync`
    directly, no network, so pipeline drains are deterministic)."""
    from tendermint_tpu.abci.apps import KVStoreApp

    fresh_state = make_genesis_state(MemDB(), sim.genesis)
    fresh_state.save()
    store = BlockStore(MemDB())
    conns = local_client_creator(app if app is not None else KVStoreApp())()
    reactor = BlockchainReactor(
        state=fresh_state,
        store=store,
        app_conn=conns.consensus,
        fast_sync=True,
        verifier=verifier,
        pipeline_depth=depth,
    )
    reactor.pool.set_peer_height("srv", len(sim.blocks))
    for h, b in enumerate(sim.blocks, start=1):
        reactor.pool._blocks[h] = (b, "srv")
    return reactor, fresh_state, store


class TestFastSyncPipeline:
    """Software-pipeline ordering: while window K's verdict is in
    flight, K+1 preps and K-1 applies — and any redo / verdict failure
    / valset boundary must drain the in-flight suffix WITHOUT applying
    stale blocks (ISSUE 4 acceptance)."""

    def test_pipelined_sync_applies_full_chain(self):
        sim = ChainSim(n_vals=4)
        for _ in range(48):
            sim.advance()
        for depth in (1, 2, 3):
            reactor, state, store = _pipelined_reactor(sim, depth=depth)
            reactor._try_sync()
            assert store.height == 47, f"depth {depth}"
            assert state.last_block_height == 47
            for h in (1, 20, 47):
                assert store.load_block(h).hash() == sim.blocks[h - 1].hash()

    def test_linkage_break_mid_pipeline_applies_intact_prefix_only(self):
        """Window 2's commit linkage breaks while window 1 is in
        flight: window 1 (verified under intact linkage) must still
        apply; the broken suffix must be dropped un-applied."""
        from tests.helpers import make_block_id

        sim = ChainSim(n_vals=4)
        for _ in range(40):
            sim.advance()
        # blocks[20] (height 21) carries height 20's commit; point it at
        # a wrong block so window-2 prep hits the linkage mismatch
        import dataclasses

        bad = dataclasses.replace(
            sim.blocks[20].last_commit, block_id=make_block_id(b"forged")
        )
        sim.blocks[20] = dataclasses.replace(sim.blocks[20], last_commit=bad)
        reactor, _state, store = _pipelined_reactor(sim, depth=2)
        reactor._try_sync()
        # window 1 = heights 1..17 peeked, 16 applied; the redo at
        # height 20 dropped the pool suffix before it could ever apply
        assert store.height == 16
        assert store.load_block(20) is None
        assert reactor.pool.height == 17
        # the bad suffix is gone from the pool: nothing stale remains
        assert all(b.header.height < 20 for b in reactor.pool.peek(50))

    def test_forged_verdict_mid_pipeline_drains_without_applying(self):
        """Window 2's commit signatures are forged: its verdict fails at
        the JOIN (after younger windows were already submitted) — the
        older window applies, the failed one and everything behind it
        drain un-applied."""
        sim = ChainSim(n_vals=4)
        for _ in range(40):
            sim.advance()
        # forge quorum-breaking signatures in height 20's commit (rides
        # in blocks[20].last_commit); linkage stays intact so the fault
        # surfaces at verdict-join time, not prep time
        commit = sim.blocks[20].last_commit
        for i in range(3):
            commit.precommits[i] = commit.precommits[i].with_signature(bytes(64))
        reactor, _state, store = _pipelined_reactor(sim, depth=2)
        reactor._try_sync()
        assert store.height == 16  # window 1 applied, window 2 rejected
        assert store.load_block(17) is None
        assert store.load_block(20) is None

    def test_valset_rotation_boundary_drains_and_crosses(self):
        """A validator-power rotation mid-chain: pipelined windows never
        span the boundary (validators_hash changes), the pipeline drains,
        `_sync_one` walks the boundary block, and sync continues under
        the new set to the chain head."""
        from tendermint_tpu.abci.apps import PersistentKVStoreApp

        sim = ChainSim(n_vals=4, app=PersistentKVStoreApp())
        for _ in range(20):
            sim.advance()
        pub = sim.state.validators.validators[0].pub_key.data.hex()
        sim.advance(txs=[f"val:{pub}/25".encode()])  # height 21 rotates power
        assert sim.state.validators.hash() != sim.blocks[0].header.validators_hash
        for _ in range(19):
            sim.advance()
        reactor, state, store = _pipelined_reactor(
            sim, depth=2, app=PersistentKVStoreApp()
        )
        reactor._try_sync()
        assert store.height == 39
        assert state.validators.hash() == sim.state.validators.hash()

    def test_device_faults_mid_pipeline_fall_back_in_order(self):
        """TENDERMINT_TPU_DEVICE_FAIL mid-pipeline: faulted in-flight
        window launches resolve via host re-verify inside their handles
        and the sync completes — every apply in height order (any
        reorder would break the app_hash/validators_hash lineage and
        stall the sync short of the head)."""
        from tendermint_tpu.services.resilient import ResilientVerifier
        from tendermint_tpu.services.verifier import TableBatchVerifier
        from tendermint_tpu.utils import fail
        from tendermint_tpu.utils.circuit import CircuitBreaker

        sim = ChainSim(n_vals=4)
        for _ in range(48):
            sim.advance()
        verifier = ResilientVerifier(
            TableBatchVerifier(min_device_batch=10**6),
            breaker=CircuitBreaker(failure_threshold=100, reset_timeout_s=60),
        )
        fail.clear_device_faults()
        fail.set_device_fault("verify", 2)  # first two window launches fault
        try:
            reactor, _state, store = _pipelined_reactor(
                sim, depth=2, verifier=verifier
            )
            reactor._try_sync()
        finally:
            fail.clear_device_faults()
        assert store.height == 47
        assert verifier._dispatch.fallback_calls == 2


def _serving_node(sim: ChainSim, store: BlockStore):
    """A node that serves `store` over the blockchain channel."""
    sw = Switch(NodeInfo(node_id="server", moniker="server", chain_id=CHAIN))
    reactor = BlockchainReactor(
        state=sim.state, store=store, app_conn=sim.conns.consensus, fast_sync=False
    )
    sw.add_reactor("blockchain", reactor)
    sw.start()
    return sw


class TestFastSyncEndToEnd:
    @pytest.mark.slow
    def test_syncs_200_block_store_into_fresh_node(self):
        # build a 200-block chain and store it
        sim = ChainSim(n_vals=4)
        store = BlockStore(MemDB())
        for _ in range(200):
            block = sim.advance()
            parts = block.make_part_set()
            store.save_block(block, parts, sim.commits[-1])
        assert store.height == 200

        server = _serving_node(sim, store)

        # fresh node: genesis state, empty store
        db = MemDB()
        fresh_state = make_genesis_state(db, sim.genesis)
        fresh_state.save()
        fresh_store = BlockStore(MemDB())
        conns = local_client_creator(KVStoreApp())()
        caught_up = []
        client_reactor = BlockchainReactor(
            state=fresh_state,
            store=fresh_store,
            app_conn=conns.consensus,
            fast_sync=True,
            on_caught_up=lambda st: caught_up.append(st.last_block_height),
        )
        client = Switch(NodeInfo(node_id="fresh", moniker="fresh", chain_id=CHAIN))
        client.add_reactor("blockchain", client_reactor)
        client.start()
        try:
            connect_switches(server, client)
            wait_until(
                lambda: fresh_store.height >= 199,
                timeout=90,
                msg="fresh node synced",
            )
            # state replicated: same app hash lineage and validators
            assert fresh_state.last_block_height >= 199
            for h in (1, 50, 199):
                assert (
                    fresh_store.load_block(h).hash() == store.load_block(h).hash()
                )
            # windows were batch-verified, not one-by-one (the device
            # batching seam): blocks_synced counts applies
            assert client_reactor.blocks_synced >= 199
            wait_until(lambda: bool(caught_up), timeout=30, msg="caught-up fired")
        finally:
            server.stop()
            client.stop()
