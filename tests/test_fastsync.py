"""Fast-sync: BlockPool scheduling + syncing a 200-block store into a
fresh node over the p2p network with window-batched commit verification
(reference `blockchain/pool_test.go`, `blockchain/reactor.go:191-289`;
BASELINE config 3 shape).
"""

import time

import pytest

from tendermint_tpu.abci.apps import KVStoreApp
from tendermint_tpu.abci.client import local_client_creator
from tendermint_tpu.blockchain import BlockchainReactor, BlockPool, BlockStore
from tendermint_tpu.db.kv import MemDB
from tendermint_tpu.p2p import NodeInfo, Switch, connect_switches
from tendermint_tpu.state import make_genesis_state

from tests.helpers import CHAIN_ID as CHAIN
from tests.helpers import ChainSim


def wait_until(pred, timeout=60.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


class TestBlockPool:
    def test_schedules_up_to_cap(self):
        pool = BlockPool(start_height=1, max_pending=8)
        pool.set_peer_height("p1", 100)
        pool.set_peer_height("p2", 100)
        reqs, evict = pool.schedule_requests(now=0.0)
        assert len(reqs) == 8 and not evict
        assert {h for _, h in reqs} == set(range(1, 9))
        # both peers get load
        assert {p for p, _ in reqs} == {"p1", "p2"}
        # nothing new while outstanding
        assert pool.schedule_requests(now=1.0) == ([], [])

    def test_timeout_evicts_peer_and_reassigns(self):
        pool = BlockPool(start_height=1, max_pending=4)
        pool.set_peer_height("p1", 100)
        reqs, evict = pool.schedule_requests(now=0.0)
        assert {p for p, _ in reqs} == {"p1"} and not evict
        pool.set_peer_height("p2", 100)
        # p1 never answers: evicted at timeout, heights rescheduled to
        # p2 in the same tick (byzantine defense: a peer advertising an
        # unserved height can no longer pin max_peer_height forever)
        reqs2, evict2 = pool.schedule_requests(now=100.0)
        assert evict2 == ["p1"]
        assert {p for p, _ in reqs2} == {"p2"}
        assert {h for _, h in reqs2} == set(range(1, 5))
        assert pool.num_peers() == 1

    def test_slow_drip_peer_evicted_below_min_recv_rate(self):
        """A peer that keeps responding but below the 10 kB/s floor is
        evicted (reference pool.go:33,121-126) while the healthy peer
        keeps the sync going — a trickle must not throttle the window."""
        import types

        clock = [0.0]
        pool = BlockPool(start_height=1, max_pending=8, time_fn=lambda: clock[0])
        pool.set_peer_height("slow", 100)
        pool.set_peer_height("fast", 100)
        reqs, evict = pool.schedule_requests(now=clock[0])
        assert not evict and {p for p, _ in reqs} == {"slow", "fast"}
        by_peer = {}
        for p, h in reqs:
            by_peer.setdefault(p, []).append(h)

        def blk(h):
            return types.SimpleNamespace(
                header=types.SimpleNamespace(height=h)
            )

        # 5 seconds pass: fast delivers all its blocks at ~40 kB/s,
        # slow drips one tiny response (~20 B/s) — alive, but a trickle
        for t in (1.0, 2.0, 3.0, 4.0, 5.0):
            clock[0] = t
            for h in by_peer["fast"]:
                pool.add_block("fast", blk(h), size=8000)
            by_peer["fast"] = []
        pool.add_block("slow", blk(by_peer["slow"][0]), size=100)

        reqs2, evict2 = pool.schedule_requests(now=clock[0])
        assert evict2 == ["slow"]
        assert pool.num_peers() == 1
        # the freed heights rescheduled to the healthy peer in-tick
        assert reqs2 and {p for p, _ in reqs2} == {"fast"}

    def test_rejects_unrequested_blocks(self):
        import types

        pool = BlockPool(start_height=1)
        pool.set_peer_height("p1", 10)
        pool.schedule_requests(now=0.0)
        fake = types.SimpleNamespace(
            header=types.SimpleNamespace(height=1)
        )
        assert not pool.add_block("stranger", fake)  # wrong peer
        req_peer = pool._requests[1].peer_id
        assert pool.add_block(req_peer, fake)
        assert pool.peek(1) == [fake]

    def test_redo_drops_suffix_and_names_peer(self):
        import types

        pool = BlockPool(start_height=1)
        pool.set_peer_height("p1", 10)
        pool.schedule_requests(now=0.0)
        for h in range(1, 4):
            blk = types.SimpleNamespace(header=types.SimpleNamespace(height=h))
            pool.add_block(pool._requests[h].peer_id, blk)
        assert len(pool.peek(3)) == 3
        bad = pool.redo(2)
        assert bad == "p1"
        assert len(pool.peek(3)) == 1  # height 1 survives


def _serving_node(sim: ChainSim, store: BlockStore):
    """A node that serves `store` over the blockchain channel."""
    sw = Switch(NodeInfo(node_id="server", moniker="server", chain_id=CHAIN))
    reactor = BlockchainReactor(
        state=sim.state, store=store, app_conn=sim.conns.consensus, fast_sync=False
    )
    sw.add_reactor("blockchain", reactor)
    sw.start()
    return sw


class TestFastSyncEndToEnd:
    @pytest.mark.slow
    def test_syncs_200_block_store_into_fresh_node(self):
        # build a 200-block chain and store it
        sim = ChainSim(n_vals=4)
        store = BlockStore(MemDB())
        for _ in range(200):
            block = sim.advance()
            parts = block.make_part_set()
            store.save_block(block, parts, sim.commits[-1])
        assert store.height == 200

        server = _serving_node(sim, store)

        # fresh node: genesis state, empty store
        db = MemDB()
        fresh_state = make_genesis_state(db, sim.genesis)
        fresh_state.save()
        fresh_store = BlockStore(MemDB())
        conns = local_client_creator(KVStoreApp())()
        caught_up = []
        client_reactor = BlockchainReactor(
            state=fresh_state,
            store=fresh_store,
            app_conn=conns.consensus,
            fast_sync=True,
            on_caught_up=lambda st: caught_up.append(st.last_block_height),
        )
        client = Switch(NodeInfo(node_id="fresh", moniker="fresh", chain_id=CHAIN))
        client.add_reactor("blockchain", client_reactor)
        client.start()
        try:
            connect_switches(server, client)
            wait_until(
                lambda: fresh_store.height >= 199,
                timeout=90,
                msg="fresh node synced",
            )
            # state replicated: same app hash lineage and validators
            assert fresh_state.last_block_height >= 199
            for h in (1, 50, 199):
                assert (
                    fresh_store.load_block(h).hash() == store.load_block(h).hash()
                )
            # windows were batch-verified, not one-by-one (the device
            # batching seam): blocks_synced counts applies
            assert client_reactor.blocks_synced >= 199
            wait_until(lambda: bool(caught_up), timeout=30, msg="caught-up fired")
        finally:
            server.stop()
            client.stop()
