#!/usr/bin/env python
"""Merge span logs + flight-recorder dumps from N nodes into one
timeline: follow a single tx/vote from ingress to commit across the
whole cluster, or replay one height's forensics.

Inputs:
  * span logs — the per-node JSONL rings `node.Node` writes under
    `<home>/data/spans.jsonl` (`telemetry/spanlog.py`); spans carrying
    a `trace` attr are distributed-trace members;
  * flight-recorder dumps — the JSON files `telemetry/flightrec.py`
    writes on invariant violations, consensus halts, or SIGUSR2;
  * launch ledgers (`--launches`) — the per-launch JSONL rings the
    device observatory persists (`telemetry/launchlog.py`); records
    carrying an exemplar trace id join the timeline as
    `device.launch` entries, attributing device time to a traced tx.

Usage:
  python tools/trace_timeline.py --spans node*/data/spans.jsonl \\
      --trace 6fa0c1b2d3e4f509
  python tools/trace_timeline.py --spans node*/data/spans.jsonl \\
      --flight flightrec-*.json --height 7 --json

Multi-node-in-process harnesses sink every node's spans into every
node's log (the tracer is process-global); the loader dedupes, so
feeding overlapping logs is always safe.
"""

from __future__ import annotations

import argparse
import glob as glob_mod
import json
import sys

# span name -> lifecycle stage shown in the timeline (the five stages
# of a tx's life plus vote/consensus forensics); unknown names fall
# back to their dotted prefix
STAGES = {
    "mempool.admission": "admission",
    "p2p.hop": "hop",
    "batcher.flush": "flush",
    "dispatch.launch": "launch",
    "device.launch": "launch",
    "tx.e2e": "commit",
    "vote.e2e": "verdict",
    "consensus.propose": "consensus",
    "consensus.prevote": "consensus",
    "consensus.precommit": "consensus",
    "consensus.commit": "consensus",
    "consensus.height": "consensus",
}


def _expand(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        hits = sorted(glob_mod.glob(p))
        out.extend(hits if hits else [p])
    return out


def load_spans(paths: list[str]) -> list[dict]:
    """Read JSONL span logs; unparseable lines (torn writes) are
    skipped; duplicates across logs (shared-process harnesses) dedupe
    on (name, start, end, trace)."""
    seen: set = set()
    out: list[dict] = []
    for path in _expand(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if not isinstance(d, dict) or "name" not in d:
                continue
            attrs = d.get("attrs") or {}
            key = (d["name"], d.get("start"), d.get("end"), attrs.get("trace"))
            if key in seen:
                continue
            seen.add(key)
            out.append(d)
    return out


def load_flight(paths: list[str]) -> list[dict]:
    """Read flight-recorder dumps; each event is tagged with the dump's
    node id (when the dumping process knew one)."""
    out: list[dict] = []
    for path in _expand(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                dump = json.load(f)
        except (OSError, ValueError):
            continue
        node = dump.get("node", "")
        for evt in dump.get("events", []):
            if isinstance(evt, dict):
                evt = dict(evt)
                evt.setdefault("node", node)
                out.append(evt)
    return out


def load_launches(paths: list[str]) -> list[dict]:
    """Read LaunchLedger JSONL files (`launches.jsonl`, the device
    observatory) and convert each record carrying an exemplar trace id
    into a span-shaped `device.launch` entry — so a traced tx's
    timeline shows the device launch its verify rode, with the rows /
    padding / stage split as attrs. Records without a trace are
    skipped (the ledger is exhaustive; the timeline is trace-scoped)."""
    out: list[dict] = []
    seen: set = set()
    for path in _expand(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            try:
                d = json.loads(line)
            except ValueError:
                continue
            if not isinstance(d, dict) or "kind" not in d or not d.get("trace"):
                continue
            end = float(d.get("t", 0.0))
            start = end - float(d.get("total_s", 0.0))
            key = ("device.launch", start, end, d["trace"])
            if key in seen:
                continue
            seen.add(key)
            attrs = {
                "trace": d["trace"],
                "node": d.get("node", ""),
                "kind": d.get("kind"),
                "backend": d.get("backend"),
                "rows": d.get("rows"),
            }
            for k in ("rows_padded", "rows_cached", "in_flight_s", "queue"):
                if d.get(k):
                    attrs[k] = d[k]
            out.append(
                {
                    "name": "device.launch",
                    "start": start,
                    "end": end,
                    "attrs": attrs,
                }
            )
    return out


def build_timeline(
    spans: list[dict],
    events: list[dict] | None = None,
    trace_id: str | None = None,
    height: int | None = None,
) -> dict:
    """One merged, time-ordered timeline. `trace_id` selects the spans
    of one distributed trace; `height` selects flight events (and, when
    no trace filter is given, spans) of one height. Flight events carry
    no trace ids — with both filters set, you get the trace's spans
    interleaved with that height's black-box events."""
    entries: list[dict] = []
    for s in spans:
        attrs = s.get("attrs") or {}
        if trace_id is not None:
            if attrs.get("trace") != trace_id:
                continue
        elif height is not None and attrs.get("height") != height:
            continue
        entries.append(
            {
                "t": float(s.get("start", 0.0)),
                "end": float(s.get("end", 0.0)),
                "kind": "span",
                "name": s["name"],
                "stage": STAGES.get(s["name"], s["name"].split(".")[0]),
                "node": str(attrs.get("node") or attrs.get("origin") or ""),
                "attrs": attrs,
            }
        )
    for e in events or []:
        if height is not None and e.get("height") != height:
            continue
        if height is None and trace_id is not None:
            continue  # flight events are height-scoped, not trace-scoped
        entries.append(
            {
                "t": float(e.get("t", 0.0)),
                "end": float(e.get("t", 0.0)),
                "kind": "event",
                "name": e.get("kind", ""),
                "stage": "flight",
                "node": str(e.get("node", "")),
                "attrs": {k: v for k, v in e.items() if k not in ("t", "kind")},
            }
        )
    entries.sort(key=lambda x: (x["t"], x["end"]))
    return {
        "trace_id": trace_id,
        "height": height,
        "entries": entries,
        "stages": sorted({x["stage"] for x in entries}),
        "nodes": sorted({x["node"] for x in entries if x["node"]}),
        "span_count": sum(1 for x in entries if x["kind"] == "span"),
        "event_count": sum(1 for x in entries if x["kind"] == "event"),
    }


def render_text(timeline: dict) -> str:
    entries = timeline["entries"]
    if not entries:
        return "(empty timeline)\n"
    t0 = entries[0]["t"]
    head = []
    if timeline.get("trace_id"):
        head.append(f"trace {timeline['trace_id']}")
    if timeline.get("height") is not None:
        head.append(f"height {timeline['height']}")
    lines = [
        " ".join(head) or "timeline",
        f"{len(entries)} entries, nodes: {', '.join(timeline['nodes']) or '-'}",
        "",
    ]
    for x in entries:
        dur_ms = (x["end"] - x["t"]) * 1e3
        attrs = " ".join(
            f"{k}={v}"
            for k, v in sorted(x["attrs"].items())
            if k not in ("trace", "node")
        )
        lines.append(
            f"+{(x['t'] - t0) * 1e3:10.3f}ms "
            f"{x['stage']:>10} {x['name']:<20} "
            f"{x['node'][:12]:<12} {dur_ms:8.3f}ms  {attrs}"
        )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--spans", nargs="+", default=[], help="span-log JSONL files (globs ok)"
    )
    ap.add_argument(
        "--flight", nargs="+", default=[], help="flight-recorder dump files (globs ok)"
    )
    ap.add_argument(
        "--launches",
        nargs="+",
        default=[],
        help="LaunchLedger JSONL files — device launches join the "
        "traced timeline (globs ok)",
    )
    ap.add_argument("--trace", default=None, help="hex trace id to follow")
    ap.add_argument("--height", type=int, default=None, help="height to replay")
    ap.add_argument("--json", action="store_true", help="emit JSON, not text")
    args = ap.parse_args(argv)
    if not args.spans and not args.flight and not args.launches:
        ap.error("need --spans, --flight, and/or --launches inputs")
    timeline = build_timeline(
        load_spans(args.spans) + load_launches(args.launches),
        load_flight(args.flight),
        trace_id=args.trace,
        height=args.height,
    )
    if args.json:
        json.dump(timeline, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_text(timeline))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
