"""Drive a chaos scenario against an in-process consensus network.

Usage:
    JAX_PLATFORMS=cpu python tools/nemesis_demo.py [--nodes 4] [--heights 3]

Runs the full nemesis playbook once, printing each phase: healthy
commits -> device-fault injection (circuit breaker trips, host fallback
keeps committing) -> fault clears (breaker re-closes) -> partition
(progress stalls, as it must) -> heal (progress resumes) -> crash +
WAL-tail corruption + restart (recovery replays). Exits non-zero if any
invariant (no-fork, commit agreement, progress) breaks.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_ingress_scenario(args) -> int:
    """Ingress-under-chaos: sustained signed-tx loadgen traffic into a
    FULL-node network's batched admission pipeline through a partition
    heal + verify-breaker trip — every tx that answered OK must commit
    (zero admitted-pool loss), no fork."""
    import itertools
    import threading

    from tendermint_tpu.crypto.keys import gen_priv_key
    from tendermint_tpu.mempool import make_signed_tx
    from tendermint_tpu.services.resilient import ResilientVerifier
    from tendermint_tpu.services.verifier import HostBatchVerifier
    from tendermint_tpu.testing import Nemesis
    from tendermint_tpu.utils import fail
    from tendermint_tpu.utils.circuit import CircuitBreaker

    def verifier_factory(_i: int) -> ResilientVerifier:
        return ResilientVerifier(
            HostBatchVerifier(),
            breaker=CircuitBreaker(failure_threshold=2, reset_timeout_s=0.5),
            max_retries=0,
        )

    priv = gen_priv_key(b"\x33" * 32)
    t_all = time.time()
    with Nemesis(
        args.nodes,
        home=tempfile.mkdtemp(prefix="nemesis-ingress-"),
        node_factory=Nemesis.full_node_factory(),
        verifier_factory=verifier_factory,
    ) as net:
        print(f"[1/5] healthy full-node network of {args.nodes} ...")
        net.wait_height(2, timeout=args.timeout)

        admitted: list[bytes] = []
        lock = threading.Lock()
        stop = threading.Event()
        seq = itertools.count()

        def pump():
            for i in seq:
                if stop.is_set() or i >= args.txs:
                    return
                tx = make_signed_tx(priv, b"demo-%d=%d" % (i, i))

                def cb(res, tx=tx):
                    if res.is_ok:
                        with lock:
                            admitted.append(tx)

                net.nodes[i % 2].node.mempool.check_tx_async(tx, cb)
                time.sleep(1.0 / args.rate)

        print(f"[2/5] open-loop signed traffic at {args.rate:.0f} tx/s ...")
        t = threading.Thread(target=pump, daemon=True)
        t.start()
        try:
            time.sleep(0.5)
            print("[3/5] partition minority + trip the verify breaker ...")
            net.partition(set(range(args.nodes - 1)), {args.nodes - 1})
            fail.set_device_fault("verify")
            net.wait_progress(
                delta=2, nodes=list(range(args.nodes - 1)), timeout=args.timeout
            )
            print("[4/5] clear fault + heal; traffic still flowing ...")
            fail.clear_device_faults()
            net.heal()
            net.wait_progress(delta=2, timeout=args.timeout)
        finally:
            stop.set()
            t.join(10)
        with lock:
            final = list(admitted)
        print(f"[5/5] draining: {len(final)} admitted txs must all commit ...")
        deadline = time.time() + args.timeout
        missing = set(final)
        while time.time() < deadline and missing:
            store = net.nodes[0].store
            committed = set()
            for h in range(max(1, store.base), store.height + 1):
                blk = store.load_block(h)
                if blk is not None:
                    committed.update(bytes(x) for x in blk.data.txs)
            missing = set(final) - committed
            if missing:
                time.sleep(0.5)
        if missing:
            print(f"FAILED: {len(missing)} admitted txs lost")
            return 1
        net.check_invariants()
        print(
            f"done in {time.time() - t_all:.1f}s; zero admitted-tx loss, "
            "no fork"
        )
    return 0


def run_byzantine_scenario(args) -> int:
    """The adversary book: each scenario runs a live network, unleashes
    one Byzantine driver from `testing/byzantine.py`, and records a
    verdict — evidence committed, attacker banned, breaker closed, no
    fork, liveness held (docs/BYZANTINE.md)."""
    import time as _time

    from tendermint_tpu.services.resilient import ResilientVerifier
    from tendermint_tpu.services.verifier import HostBatchVerifier
    from tendermint_tpu.telemetry import REGISTRY
    from tendermint_tpu.testing import (
        ConflictingProposer,
        Equivocator,
        FrameFuzzer,
        GarbageSigFlooder,
        Nemesis,
    )
    from tendermint_tpu.testing.byzantine import wait_evidence_committed
    from tendermint_tpu.utils.circuit import CircuitBreaker

    def verifier_factory(_i):
        return ResilientVerifier(
            HostBatchVerifier(),
            breaker=CircuitBreaker(failure_threshold=2, reset_timeout_s=0.5),
            max_retries=0,
        )

    verdicts: list[tuple[str, str, str]] = []  # (scenario, verdict, detail)
    t_all = _time.time()
    with Nemesis(
        args.nodes,
        home=tempfile.mkdtemp(prefix="nemesis-byz-"),
        verifier_factory=verifier_factory,
    ) as net:
        print(f"[1/4] equivocating validator (node {args.nodes - 1}) ...")
        net.wait_height(2, timeout=args.timeout)
        eq = Equivocator(net, args.nodes - 1).start()
        try:
            honest = list(range(args.nodes - 1))
            found = wait_evidence_committed(
                net, eq.address, nodes=honest, within_heights=5,
                timeout=args.timeout,
            )
            verdicts.append(
                (
                    "equivocator",
                    "PASS",
                    f"{eq.equivocations} double-signs -> evidence committed "
                    f"at heights {sorted(set(found.values()))} on all "
                    f"{len(honest)} honest nodes (<= 5 heights late)",
                )
            )
        finally:
            eq.stop()

        print("[2/4] conflicting proposer (node 1) ...")
        cp = ConflictingProposer(net, 1).start()
        try:
            deadline = _time.time() + args.timeout
            while _time.time() < deadline and cp.conflicts < 2:
                _time.sleep(0.05)
            net.wait_progress(delta=3, timeout=args.timeout)
            net.check_invariants()
            verdicts.append(
                (
                    "conflicting-proposer",
                    "PASS",
                    f"{cp.conflicts} split proposals; no fork, progress held",
                )
            )
        finally:
            cp.stop()

        print("[3/4] garbage-signature flooder vs node 0 ...")
        trips_before = REGISTRY.counter_value(
            "tendermint_breaker_transitions_total", kind="verify", to="open"
        )
        flooder = GarbageSigFlooder(net.nodes[0], net.chain_id)
        try:
            deadline = _time.time() + args.timeout
            while _time.time() < deadline and not flooder.banned():
                flooder.flood_votes(64)
                flooder.flood_txs(64)
                _time.sleep(0.05)
            trips = (
                REGISTRY.counter_value(
                    "tendermint_breaker_transitions_total",
                    kind="verify",
                    to="open",
                )
                - trips_before
            )
            banned = flooder.banned() and not flooder.reconnect()
            breakers = [n.cs.verifier.breaker.state for n in net.nodes]
            ok = banned and trips == 0 and all(s == "closed" for s in breakers)
            verdicts.append(
                (
                    "sig-flooder",
                    "PASS" if ok else "FAIL",
                    f"banned={banned}, breaker trips={trips:.0f}, "
                    f"states={breakers}",
                )
            )
            net.wait_progress(delta=2, timeout=args.timeout)
        finally:
            flooder.stop()

        print("[4/4] wire-frame fuzzer vs node 1 ...")
        fuzzer = FrameFuzzer(net.nodes[1].switch, net.chain_id)
        sent = fuzzer.run(args.fuzz_frames)
        fuzzer.stop()
        net.wait_progress(delta=1, timeout=args.timeout)
        net.check_invariants()
        verdicts.append(
            (
                "frame-fuzzer",
                "PASS",
                f"{sent} mutated frames across {fuzzer.reconnects} "
                f"identities; node alive, no fork",
            )
        )

    print(f"\nadversary book done in {_time.time() - t_all:.1f}s:")
    width = max(len(s) for s, _, _ in verdicts)
    failed = 0
    for scenario, verdict, detail in verdicts:
        print(f"  {scenario:<{width}}  {verdict}  {detail}")
        failed += verdict != "PASS"
    return 1 if failed else 0


def run_pipeline_scenario(args) -> int:
    """Cross-height pipeline chaos book (ROADMAP item 3's gate): a
    FAULTED apply landing mid-pipeline drains at the join barrier and
    halts its node with no speculative state persisted; a FORGED apply
    (diverged local execution) can never fork the chain — the honest
    +2/3 keeps committing honest headers while the forger wedges
    itself. Restarting the faulted node proves the drain left a
    recoverable WAL/store. No-fork + commit-agreement invariants run
    continuously."""
    from tendermint_tpu.state.state import load_state
    from tendermint_tpu.testing import Nemesis
    from tendermint_tpu.testing.nemesis import (
        FaultedApplyApp,
        ForgedHashApp,
        one_bad_app_factory,
    )

    t_all = time.time()
    verdicts: list[tuple[str, str, str]] = []

    def wait_fatal(node, timeout=30.0):
        deadline = time.time() + timeout
        while node.cs.fatal_error is None and time.time() < deadline:
            time.sleep(0.1)
        return node.cs.fatal_error

    print("[1/2] faulted apply mid-pipeline: node 3's ABCI commit raises at height 4 ...")
    with Nemesis(
        args.nodes,
        home=tempfile.mkdtemp(prefix="nemesis-pipe-"),
        node_factory=Nemesis.full_node_factory(
            app_factory=one_bad_app_factory(
                3, FaultedApplyApp, args.nodes, fail_from_height=4
            )
        ),
    ) as net:
        honest = list(range(args.nodes - 1))
        net.wait_height(6, nodes=honest, timeout=args.timeout)
        err = wait_fatal(net.nodes[3])
        persisted = load_state(net.nodes[3].node.state_db).last_block_height
        net.check_no_fork()
        ok = err is not None and persisted == 3
        verdicts.append(
            (
                "faulted apply",
                "PASS" if ok else "FAIL",
                f"halted={err is not None} persisted_height={persisted} "
                f"(speculative height 4 never landed), honest chain at "
                f"{max(net.heights())}, no fork",
            )
        )

    print("[2/2] forged apply: node 3's app returns a forged app hash from height 3 ...")
    with Nemesis(
        args.nodes,
        home=tempfile.mkdtemp(prefix="nemesis-forge-"),
        node_factory=Nemesis.full_node_factory(
            app_factory=one_bad_app_factory(
                3, ForgedHashApp, args.nodes, fail_from_height=3
            )
        ),
    ) as net:
        honest = list(range(args.nodes - 1))
        net.wait_height(6, nodes=honest, timeout=args.timeout)
        err = wait_fatal(net.nodes[3])
        forged = b"\xde\xad\xbe\xef" * 5
        clean = all(
            net.nodes[0].store.load_block_meta(h).header.app_hash != forged
            for h in range(4, net.nodes[0].store.height + 1)
        )
        net.check_no_fork()
        ok = err is not None and clean
        verdicts.append(
            (
                "forged apply",
                "PASS" if ok else "FAIL",
                f"forger halted={err is not None}, no committed header "
                f"carries the forged hash={clean}, honest chain at "
                f"{max(net.heights())}, no fork",
            )
        )

    print(f"\npipeline chaos book done in {time.time() - t_all:.1f}s:")
    width = max(len(s) for s, _, _ in verdicts)
    failed = 0
    for scenario, verdict, detail in verdicts:
        print(f"  {scenario:<{width}}  {verdict}  {detail}")
        failed += verdict != "PASS"
    return 1 if failed else 0


def run_replicas_scenario(args) -> int:
    """Read-replica fleet book (ROADMAP item 1's gate): stateless
    replicas join a live validator net, follow it via follow-mode
    fast-sync + the 0x68 FullCommit subscription, and serve
    light-client reads. Then (a) a forged-FullCommit pusher attacks a
    replica — the client pin rejects it, the pusher is banned, and the
    embedded double-sign becomes COMMITTED evidence on the validators
    while the honest replica keeps answering; (b) the replica fleet is
    partitioned from the validators — serving lag is reported, reads
    keep answering from the certified cache, and the fleet converges
    after heal."""
    import json as _json

    from tendermint_tpu.testing import Nemesis
    from tendermint_tpu.testing.byzantine import (
        ForgedCommitPusher,
        forge_fullcommit,
        wait_evidence_committed,
    )
    from tendermint_tpu.testing.nemesis import FullNemesisNode

    t_all = time.time()
    verdicts: list[tuple[str, str, str]] = []
    home = tempfile.mkdtemp(prefix="nemesis-replicas-")

    def replica_mutator(cfg):
        cfg.replica.enable = True

    with Nemesis(
        args.nodes, home=home, node_factory=Nemesis.full_node_factory()
    ) as net:
        n_vals = args.nodes
        print(f"[1/4] {n_vals} validators + {args.replicas} joining replicas ...")
        net.wait_height(2, timeout=args.timeout)
        reps = []
        for k in range(args.replicas):
            rep = FullNemesisNode(
                n_vals + k,
                net.genesis,
                net.privs,
                home,
                net.chain_id,
                config_mutator=replica_mutator,
            )
            net.add_node(rep)
            reps.append(rep)
        rep_idx = [n_vals + k for k in range(args.replicas)]
        target = max(net.heights()) + 2
        net.wait_height(target, nodes=rep_idx, timeout=args.timeout)
        certified = [r.node.fullcommit_cache.latest_height() for r in reps]
        deadline = time.time() + args.timeout
        while time.time() < deadline and not all(c >= 2 for c in certified):
            time.sleep(0.2)
            certified = [r.node.fullcommit_cache.latest_height() for r in reps]
        verdicts.append(
            (
                "replica-follow",
                "PASS" if all(c >= 2 for c in certified) else "FAIL",
                f"replicas at heights {[r.height for r in reps]}, certified "
                f"tips {certified}, consensus never joined "
                f"({all(r.node.consensus is None for r in reps)})",
            )
        )

        print("[2/4] forged FullCommit pushed at replica 0 ...")
        honest = reps[0].node.lightclient_reactor.serve_commit(2)
        forged = forge_fullcommit(honest, net.privs[0], net.chain_id)
        pusher = ForgedCommitPusher(reps[0].node, forged)
        pusher.push()
        try:
            deadline = time.time() + args.timeout
            while time.time() < deadline and not pusher.banned():
                time.sleep(0.1)
            # the embedded double-sign must COMMIT on the validators
            found = wait_evidence_committed(
                net,
                net.privs[0].address,
                nodes=list(range(n_vals)),
                timeout=args.timeout,
            )
            # the honest replica still answers at the attacked height
            served = reps[1].node.lightclient_reactor.serve_commit(2)
            honest_ok = (
                served is not None
                and served.header.app_hash == honest.header.app_hash
            )
            ok = pusher.banned() and honest_ok
            verdicts.append(
                (
                    "forged-fullcommit",
                    "PASS" if ok else "FAIL",
                    f"pusher banned={pusher.banned()}, double-sign evidence "
                    f"committed at heights {sorted(set(found.values()))}, "
                    f"honest replica answers={honest_ok}",
                )
            )
        finally:
            pusher.stop()

        print(f"[3/4] partition validators | replicas ...")
        net.partition(set(range(n_vals)), set(rep_idx))
        net.wait_progress(delta=2, nodes=list(range(n_vals)), timeout=args.timeout)
        stale = [r.height for r in reps]
        # reads keep answering from the certified cache while cut off
        served = reps[0].node.lightclient_reactor.serve_commit(0)
        lag_reported = [
            r.node.health()["serving"]["serving_lag"] for r in reps
        ]
        verdicts.append(
            (
                "partitioned-serving",
                "PASS" if served is not None else "FAIL",
                f"replica heights frozen at {stale} while validators "
                f"advanced to {max(net.heights())}; cached tip still "
                f"served (h={served.height() if served else None}), "
                f"serving lag reported {lag_reported}",
            )
        )

        print("[4/4] heal; replica fleet must converge ...")
        net.heal()
        target = max(net.heights()[:n_vals])
        net.wait_height(target, nodes=rep_idx, timeout=args.timeout)
        summary = {
            "heights": net.heights(),
            "certified": [r.node.fullcommit_cache.latest_height() for r in reps],
        }
        verdicts.append(
            ("partition-heal", "PASS", _json.dumps(summary, separators=(",", ":")))
        )
        net.check_invariants()

    print(f"\nreplica book done in {time.time() - t_all:.1f}s:")
    width = max(len(s) for s, _, _ in verdicts)
    failed = 0
    for scenario, verdict, detail in verdicts:
        print(f"  {scenario:<{width}}  {verdict}  {detail}")
        failed += verdict != "PASS"
    return 1 if failed else 0


def run_scenarios_scenario(args) -> int:
    """Declarative scenario-library book: every library entry — WAN
    slow-validator, validator churn, flash crowd, regional outage,
    churn storm, partition-during-churn — runs end-to-end through the
    ScenarioRunner and is graded against its committed expectations
    (finality SLOs, epoch counts, adaptive-timeout convergence,
    light-client bisection across rotations)."""
    from tendermint_tpu.testing.scenario import run_library

    t_all = time.time()
    home = tempfile.mkdtemp(prefix="nemesis-scenarios-")
    reports = run_library(home=home, include_slow=not args.fast)
    verdicts: list[tuple[str, str, str]] = []
    for report in reports:
        fin = report["finality"]
        detail = f"heights {report['heights']}"
        if fin.get("count"):
            detail += f", finality p95 {fin['p95_s']:.2f}s"
        if "epochs" in report:
            detail += (
                f", {report['epochs']} epochs / "
                f"{report['valset_rebuilds']} rebuilds"
            )
        if "bisection" in report:
            detail += f", bisected to h{report['bisection']['verified_to']}"
        g = report.get("gossip")
        if g:
            detail += f", gossip {g['total_bytes'] / 1e6:.1f}MB"
            if g["redundancy_factor"]:
                detail += " (" + ", ".join(
                    f"{k} {f:.1f}x dup"
                    for k, f in sorted(
                        g["redundancy_factor"].items(), key=lambda kv: -kv[1]
                    )
                ) + ")"
        if report["failures"]:
            detail += f" — {'; '.join(report['failures'])}"
        verdicts.append(
            (report["scenario"], "PASS" if report["ok"] else "FAIL", detail)
        )

    print(f"\nscenario book done in {time.time() - t_all:.1f}s:")
    width = max(len(s) for s, _, _ in verdicts)
    failed = 0
    for scenario, verdict, detail in verdicts:
        print(f"  {scenario:<{width}}  {verdict}  {detail}")
        failed += verdict != "PASS"
    # the gossip verdict table: per-channel bandwidth + per-kind
    # redundancy, fleet-summed across the book's scenarios (the same
    # rollup tools/gossip_report.py renders per node)
    chan_totals: dict[str, int] = {}
    red_totals: dict[str, dict] = {}
    for report in reports:
        g = report.get("gossip")
        if not g:
            continue
        for c, b in g["channel_bytes"].items():
            chan_totals[c] = chan_totals.get(c, 0) + b
        for k, st in g["redundant"].items():
            r = red_totals.setdefault(k, {"msgs": 0, "bytes": 0})
            r["msgs"] += st["msgs"]
            r["bytes"] += st["bytes"]
    if chan_totals:
        print("\ngossip verdict (book total):")
        for c, b in sorted(chan_totals.items(), key=lambda kv: -kv[1]):
            print(f"  {c:<14} {b / 1e6:>8.2f}MB")
        for k, r in sorted(red_totals.items(), key=lambda kv: -kv[1]["bytes"]):
            print(
                f"  redundant {k:<11} {r['msgs']:>6} msgs "
                f"{r['bytes'] / 1e3:>8.1f}kB"
            )
    return 1 if failed else 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--heights", type=int, default=3, help="heights per phase")
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument(
        "--ingress",
        action="store_true",
        help="run the ingress-under-chaos scenario (full nodes + loadgen "
        "traffic through partition heal + breaker trip) instead",
    )
    ap.add_argument(
        "--byzantine",
        action="store_true",
        help="run the Byzantine adversary book (equivocator -> evidence "
        "committed; flooder -> banned, breaker closed; proposer "
        "equivocation; frame fuzzing) instead",
    )
    ap.add_argument(
        "--pipeline",
        action="store_true",
        help="run the cross-height pipeline chaos book (faulted apply "
        "drains at the join barrier; forged apply cannot fork) instead",
    )
    ap.add_argument(
        "--replicas",
        type=int,
        default=0,
        help="run the read-replica fleet book with this many replicas "
        "(forged-FullCommit attribution; fleet under partition) instead",
    )
    ap.add_argument(
        "--scenarios",
        action="store_true",
        help="run the declarative scenario-library book (WAN topologies, "
        "validator churn, flash crowd, regional outage) instead",
    )
    ap.add_argument(
        "--fast",
        action="store_true",
        help="with --scenarios: tier-1 entries only, skip the slow ones",
    )
    ap.add_argument("--rate", type=float, default=150.0, help="ingress tx/s")
    ap.add_argument("--txs", type=int, default=1000, help="ingress tx cap")
    ap.add_argument(
        "--fuzz-frames", type=int, default=5000, help="byzantine fuzz frame count"
    )
    args = ap.parse_args()

    if args.ingress:
        from tendermint_tpu.utils.log import setup_logging

        setup_logging("resilient:info,nemesis:info,*:error")
        return run_ingress_scenario(args)

    if args.byzantine:
        from tendermint_tpu.utils.log import setup_logging

        setup_logging("byzantine:info,evidence:warning,nemesis:info,*:error")
        return run_byzantine_scenario(args)

    if args.pipeline:
        from tendermint_tpu.utils.log import setup_logging

        setup_logging("nemesis:info,*:error")
        return run_pipeline_scenario(args)

    if args.scenarios:
        from tendermint_tpu.utils.log import setup_logging

        setup_logging("scenario:info,nemesis:warning,*:error")
        return run_scenarios_scenario(args)

    if args.replicas > 0:
        from tendermint_tpu.utils.log import setup_logging

        setup_logging("lightclient:warning,nemesis:info,*:error")
        return run_replicas_scenario(args)

    from tendermint_tpu.services.resilient import ResilientVerifier
    from tendermint_tpu.services.verifier import HostBatchVerifier
    from tendermint_tpu.testing import Nemesis
    from tendermint_tpu.utils import fail
    from tendermint_tpu.utils.circuit import CircuitBreaker
    from tendermint_tpu.utils.log import setup_logging

    setup_logging("resilient:info,nemesis:info,*:error")

    def verifier_factory(_i: int) -> ResilientVerifier:
        return ResilientVerifier(
            HostBatchVerifier(),
            breaker=CircuitBreaker(failure_threshold=3, reset_timeout_s=1.0),
            max_retries=0,
        )

    t_all = time.time()
    with Nemesis(
        args.nodes, home=tempfile.mkdtemp(prefix="nemesis-demo-"),
        verifier_factory=verifier_factory,
    ) as net:
        step = args.heights

        print(f"[1/6] healthy network of {args.nodes} ...")
        net.wait_height(step, timeout=args.timeout)

        print("[2/6] injecting device verify faults (breaker will trip) ...")
        fail.set_device_fault("verify")
        target = max(net.heights()) + step
        net.wait_height(target, timeout=args.timeout)
        states = [n.cs.verifier.breaker.state for n in net.nodes]
        print(f"      breaker states: {states}; committing on host fallback")

        print("[3/6] clearing faults (breaker re-closes on probe) ...")
        fail.clear_device_faults()
        target = max(net.heights()) + step
        net.wait_height(target, timeout=args.timeout)
        deadline = time.time() + 30
        while time.time() < deadline and any(
            n.cs.verifier.breaker.state != "closed" for n in net.nodes
        ):
            time.sleep(0.2)
        print(f"      breaker states: {[n.cs.verifier.breaker.state for n in net.nodes]}")

        half = args.nodes // 2
        print(f"[4/6] partition {{0..{half-1}}} | {{{half}..{args.nodes-1}}} (no quorum, stall expected) ...")
        net.partition(set(range(half)), set(range(half, args.nodes)))
        before = max(net.heights())
        time.sleep(2.0)
        print(f"      heights {before} -> {max(net.heights())} while split")

        print("[5/6] heal (progress must resume) ...")
        net.heal()
        net.wait_height(max(net.heights()) + step, timeout=args.timeout)

        print("[6/6] crash node 0, corrupt its WAL tail, restart ...")
        net.crash(0)
        net.corrupt_wal_tail(0)
        net.restart(0)
        net.wait_height(max(net.heights()) + 1, timeout=args.timeout)

        print(
            f"done in {time.time() - t_all:.1f}s; heights={net.heights()}; "
            "all invariants held"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
