"""Drive a chaos scenario against an in-process consensus network.

Usage:
    JAX_PLATFORMS=cpu python tools/nemesis_demo.py [--nodes 4] [--heights 3]

Runs the full nemesis playbook once, printing each phase: healthy
commits -> device-fault injection (circuit breaker trips, host fallback
keeps committing) -> fault clears (breaker re-closes) -> partition
(progress stalls, as it must) -> heal (progress resumes) -> crash +
WAL-tail corruption + restart (recovery replays). Exits non-zero if any
invariant (no-fork, commit agreement, progress) breaks.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=4)
    ap.add_argument("--heights", type=int, default=3, help="heights per phase")
    ap.add_argument("--timeout", type=float, default=120.0)
    args = ap.parse_args()

    from tendermint_tpu.services.resilient import ResilientVerifier
    from tendermint_tpu.services.verifier import HostBatchVerifier
    from tendermint_tpu.testing import Nemesis
    from tendermint_tpu.utils import fail
    from tendermint_tpu.utils.circuit import CircuitBreaker
    from tendermint_tpu.utils.log import setup_logging

    setup_logging("resilient:info,nemesis:info,*:error")

    def verifier_factory(_i: int) -> ResilientVerifier:
        return ResilientVerifier(
            HostBatchVerifier(),
            breaker=CircuitBreaker(failure_threshold=3, reset_timeout_s=1.0),
            max_retries=0,
        )

    t_all = time.time()
    with Nemesis(
        args.nodes, home=tempfile.mkdtemp(prefix="nemesis-demo-"),
        verifier_factory=verifier_factory,
    ) as net:
        step = args.heights

        print(f"[1/6] healthy network of {args.nodes} ...")
        net.wait_height(step, timeout=args.timeout)

        print("[2/6] injecting device verify faults (breaker will trip) ...")
        fail.set_device_fault("verify")
        target = max(net.heights()) + step
        net.wait_height(target, timeout=args.timeout)
        states = [n.cs.verifier.breaker.state for n in net.nodes]
        print(f"      breaker states: {states}; committing on host fallback")

        print("[3/6] clearing faults (breaker re-closes on probe) ...")
        fail.clear_device_faults()
        target = max(net.heights()) + step
        net.wait_height(target, timeout=args.timeout)
        deadline = time.time() + 30
        while time.time() < deadline and any(
            n.cs.verifier.breaker.state != "closed" for n in net.nodes
        ):
            time.sleep(0.2)
        print(f"      breaker states: {[n.cs.verifier.breaker.state for n in net.nodes]}")

        half = args.nodes // 2
        print(f"[4/6] partition {{0..{half-1}}} | {{{half}..{args.nodes-1}}} (no quorum, stall expected) ...")
        net.partition(set(range(half)), set(range(half, args.nodes)))
        before = max(net.heights())
        time.sleep(2.0)
        print(f"      heights {before} -> {max(net.heights())} while split")

        print("[5/6] heal (progress must resume) ...")
        net.heal()
        net.wait_height(max(net.heights()) + step, timeout=args.timeout)

        print("[6/6] crash node 0, corrupt its WAL tail, restart ...")
        net.crash(0)
        net.corrupt_wal_tail(0)
        net.restart(0)
        net.wait_height(max(net.heights()) + 1, timeout=args.timeout)

        print(
            f"done in {time.time() - t_all:.1f}s; heights={net.heights()}; "
            "all invariants held"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
