"""Micro-probes for the verify epilogue + memory system on the bench device."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.ops.ed25519_kernel import (
    fe_canon,
    fe_carry,
    fe_invert,
    fe_mul,
    fe_to_bytes,
)
from tendermint_tpu.ops.ed25519_tables import fe_batch_invert


def timeit(fn, *args, reps=3):
    np.asarray(fn(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        np.asarray(fn(*args))
        best = min(best, time.time() - t0)
    return best


def main():
    rng = np.random.default_rng(0)
    B = 163_840

    one = jnp.asarray(rng.integers(1, 8192, size=(1, 20), dtype=np.int32))
    t = timeit(jax.jit(lambda a: fe_invert(a).sum()), one)
    print(f"fe_invert (1,20): {t*1e3:.1f}ms", flush=True)

    z = jnp.asarray(rng.integers(1, 8192, size=(B, 20), dtype=np.int32))
    t = timeit(jax.jit(lambda a: fe_batch_invert(a).sum()), z)
    print(f"fe_batch_invert ({B},20): {t*1e3:.1f}ms", flush=True)

    t = timeit(jax.jit(lambda a: fe_canon(a).sum()), z)
    print(f"fe_canon ({B},20): {t*1e3:.1f}ms", flush=True)

    t = timeit(jax.jit(lambda a: fe_to_bytes(a).sum()), z)
    print(f"fe_to_bytes ({B},20): {t*1e3:.1f}ms", flush=True)

    t = timeit(jax.jit(lambda a, b: fe_mul(a, b).sum()), z, z)
    print(f"fe_mul ({B},20): {t*1e3:.1f}ms", flush=True)

    t = timeit(jax.jit(lambda a, b: fe_carry(a + b).sum()), z, z)
    print(f"fe_addc ({B},20): {t*1e3:.1f}ms", flush=True)

    big = jnp.asarray(rng.integers(0, 100, size=(256 * 1024 * 1024,), dtype=np.int32))  # 1 GiB
    t = timeit(jax.jit(lambda a: a.sum()), big)
    print(f"sum 1GiB: {t*1e3:.1f}ms -> {1.0/t:.1f} GiB/s read", flush=True)

    t = timeit(jax.jit(lambda a: (a + 1).sum()), big)
    print(f"add+sum 1GiB: {t*1e3:.1f}ms", flush=True)

    # dependent tiny-op chain cost (scan of 100 adds on (1,20))
    def chain(a):
        def step(c, _):
            return fe_carry(c + c), None

        out, _ = jax.lax.scan(step, a, None, length=100)
        return out.sum()

    t = timeit(jax.jit(chain), one)
    print(f"100-step scan fe_carry (1,20): {t*1e3:.1f}ms", flush=True)


if __name__ == "__main__":
    main()
