#!/usr/bin/env python
"""Per-height finality waterfall across N nodes' height ledgers.

Merges the JSONL ledgers `node.Node` / the nemesis harness write under
each node's data dir (`heights.jsonl`, `telemetry/heightlog.py`) — or
the `heightledger-*.json` dumps written on invariant violations — into
one per-height view (the `trace_timeline.py` merge discipline applied
to finality): every node's commit-to-commit gap, phase decomposition,
critical-path label, pipelined-apply overlap (`ovl=` — how much of the
ABCI apply ran under the next height's voting), and the **laggard
validator** whose vote arrived last, plus an aggregate summary
(per-phase means, critical-path histogram, laggard leaderboard,
pipelined-height count + mean overlap).

Usage:
  python tools/finality_report.py --ledgers node*/data/heights.jsonl
  python tools/finality_report.py --ledgers heightledger-*.json \\
      --height 7 --json
"""

from __future__ import annotations

import argparse
import glob as glob_mod
import json
import sys
from collections import defaultdict


def _expand(paths: list[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        hits = sorted(glob_mod.glob(p))
        out.extend(hits if hits else [p])
    return out


def load_records(paths: list[str]) -> list[dict]:
    """Read ledger files: JSONL rings (one record per line) or
    `dump_all` JSON dumps ({"ledgers": [{"node", "records"}]}).
    Duplicates across overlapping inputs (a restart reloads its tail;
    dumps overlap live files) dedupe on (node, height) keeping the
    newest commit time."""
    best: dict[tuple, dict] = {}

    def _take(rec: dict) -> None:
        if not isinstance(rec, dict) or "height" not in rec:
            return
        key = (rec.get("node", ""), rec["height"])
        cur = best.get(key)
        if cur is None or rec.get("t_commit", 0.0) >= cur.get("t_commit", 0.0):
            best[key] = rec

    for path in _expand(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        stripped = text.lstrip()
        if stripped.startswith("{"):
            try:
                dump = json.loads(text)
            except ValueError:
                dump = None
            if isinstance(dump, dict) and "ledgers" in dump:
                for led in dump.get("ledgers", []):
                    node = led.get("node", "")
                    for rec in led.get("records", []):
                        if isinstance(rec, dict):
                            rec.setdefault("node", node)
                            _take(rec)
                continue
        for line in text.splitlines():
            try:
                _take(json.loads(line))
            except ValueError:
                continue
    return sorted(
        best.values(), key=lambda r: (r["height"], r.get("node", ""))
    )


def build_report(
    records: list[dict], height: int | None = None, last: int | None = None
) -> dict:
    """The merged waterfall: per-height rows (one per node) + aggregate
    summary. `height` selects one height; `last` keeps the newest N
    heights."""
    by_height: dict[int, list[dict]] = defaultdict(list)
    for r in records:
        if height is not None and r["height"] != height:
            continue
        by_height[r["height"]].append(r)
    heights = sorted(by_height)
    if last is not None:
        heights = heights[-last:]

    phase_sums: dict[str, list] = defaultdict(lambda: [0.0, 0])
    path_counts: dict[str, int] = defaultdict(int)
    laggards: dict[str, int] = defaultdict(int)
    gaps: list[float] = []
    pipelined_n = 0
    overlap_sum = 0.0
    rows = {}
    for h in heights:
        nodes = []
        for r in by_height[h]:
            gap = r.get("finality_s")
            if isinstance(gap, (int, float)):
                gaps.append(gap)
            for name, p in (r.get("phases") or {}).items():
                s = p.get("s", 0.0) if isinstance(p, dict) else float(p)
                acc = phase_sums[name]
                acc[0] += s
                acc[1] += 1
            label = r.get("critical_path")
            if label:
                path_counts[label] += 1
            lag = r.get("laggard")
            if isinstance(lag, dict) and lag.get("validator"):
                laggards[lag["validator"]] += 1
            if r.get("pipelined"):
                pipelined_n += 1
                overlap_sum += r.get("apply_overlap_s") or 0.0
            nodes.append(r)
        rows[h] = nodes
    gaps.sort()

    def _pctl(vals, q):
        if not vals:
            return None
        idx = min(len(vals) - 1, max(0, int(q * len(vals))))
        return round(vals[idx] * 1e3, 3)

    return {
        "heights": rows,
        "summary": {
            "heights": len(heights),
            "nodes": sorted({r.get("node", "") for rs in rows.values() for r in rs}),
            "finality_ms": {
                "p50": _pctl(gaps, 0.5),
                "p99": _pctl(gaps, 0.99),
                "samples": len(gaps),
            },
            "phase_mean_ms": {
                name: round(acc[0] / acc[1] * 1e3, 3)
                for name, acc in sorted(phase_sums.items())
                if acc[1]
            },
            "critical_path_counts": dict(
                sorted(path_counts.items(), key=lambda kv: -kv[1])
            ),
            "laggard_counts": dict(
                sorted(laggards.items(), key=lambda kv: -kv[1])
            ),
            "pipelined_heights": pipelined_n,
            "apply_overlap_ms_mean": round(overlap_sum / pipelined_n * 1e3, 3)
            if pipelined_n
            else None,
        },
    }


_PHASE_ORDER = ("new_height", "propose", "prevote", "precommit", "commit", "apply")
_PHASE_ABBR = {"new_height": "nh", "propose": "prop", "prevote": "pv",
               "precommit": "pc", "commit": "com", "apply": "apl"}


def render_text(report: dict) -> str:
    lines: list[str] = []
    for h, nodes in report["heights"].items():
        gaps = [
            r["finality_s"]
            for r in nodes
            if isinstance(r.get("finality_s"), (int, float))
        ]
        span = (
            f"finality {min(gaps) * 1e3:.1f}..{max(gaps) * 1e3:.1f} ms"
            if gaps
            else "finality n/a (first height)"
        )
        lines.append(f"height {h}  ({len(nodes)} nodes)  {span}")
        for r in nodes:
            phases = r.get("phases") or {}
            bar = " ".join(
                f"{_PHASE_ABBR[p]}={phases[p]['s'] * 1e3:.1f}"
                for p in _PHASE_ORDER
                if p in phases
            )
            gap = r.get("finality_s")
            gap_s = f"{gap * 1e3:8.1f}ms" if isinstance(gap, (int, float)) else "       --"
            lag = r.get("laggard")
            lag_s = (
                f"  laggard={lag['validator']}(+{lag['delay_s'] * 1e3:.1f}ms)"
                if isinstance(lag, dict)
                else ""
            )
            ovl_s = (
                f"  ovl={(r.get('apply_overlap_s') or 0.0) * 1e3:.1f}ms"
                if r.get("pipelined")
                else ""
            )
            lines.append(
                f"  {r.get('node', '?'):<14} {gap_s}  [{bar}]  "
                f"path={r.get('critical_path', '?')}{ovl_s}{lag_s}"
            )
    s = report["summary"]
    lines.append("")
    lines.append(
        f"summary: {s['heights']} heights x {len(s['nodes'])} nodes, "
        f"finality p50={s['finality_ms']['p50']}ms p99={s['finality_ms']['p99']}ms"
    )
    lines.append(
        "phase means (ms): "
        + " ".join(f"{k}={v}" for k, v in s["phase_mean_ms"].items())
    )
    lines.append(
        "critical path: "
        + (
            " ".join(f"{k}x{v}" for k, v in s["critical_path_counts"].items())
            or "-"
        )
    )
    lines.append(
        "laggards: "
        + (" ".join(f"{k}x{v}" for k, v in s["laggard_counts"].items()) or "-")
    )
    if s.get("pipelined_heights"):
        lines.append(
            f"pipeline: {s['pipelined_heights']} pipelined records, "
            f"apply overlap mean {s['apply_overlap_ms_mean']}ms"
        )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--ledgers",
        nargs="+",
        required=True,
        help="heights.jsonl files and/or heightledger-*.json dumps (globs ok)",
    )
    ap.add_argument("--height", type=int, default=None, help="one height only")
    ap.add_argument("--last", type=int, default=None, help="newest N heights")
    ap.add_argument("--json", action="store_true", help="emit JSON, not text")
    args = ap.parse_args(argv)
    report = build_report(
        load_records(args.ledgers), height=args.height, last=args.last
    )
    if args.json:
        json.dump(report, sys.stdout, indent=2)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_text(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
