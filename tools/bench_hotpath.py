"""Hot-path bench driven THROUGH the telemetry registry.

Exercises the verify/hash service backends and the consensus-WAL fsync
path, then derives `BENCH_hotpath.json` from the same histograms the
node exports on `GET /metrics` — so bench numbers and production
telemetry can never disagree about what was measured.

Backend selection is automatic: on CPU (`JAX_PLATFORMS=cpu`, the CI
shape) only the host backends run — no XLA kernel compiles, finishes in
seconds. On a TPU backend the device verifier, the valset-table
verifier, and the device Merkle tree run too (first run pays compiles
unless the persistent executable cache is warm).

    JAX_PLATFORMS=cpu python tools/bench_hotpath.py          # CI shape
    python tools/bench_hotpath.py --out BENCH_hotpath.json   # device shape

Output: one JSON line on stdout + the JSON file (default
`BENCH_hotpath.json` in the CWD).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.utils.jax_cache import enable_persistent_cache

enable_persistent_cache()


def _make_sigs(n: int):
    from tendermint_tpu.crypto.keys import gen_priv_key

    privs = [gen_priv_key(bytes([i % 256]) * 32) for i in range(min(64, n))]
    msgs = [
        b'{"chain_id":"hotpath","vote":{"height":7,"round":0,"index":%d}}' % i
        for i in range(n)
    ]
    sigs = [privs[i % len(privs)].sign(m) for i, m in enumerate(msgs)]
    pubs = [privs[i % len(privs)].pub_key.data for i in range(n)]
    return pubs, msgs, sigs


def drive_verify_host(sizes, reps) -> None:
    from tendermint_tpu.services.verifier import HostBatchVerifier

    v = HostBatchVerifier()
    for n in sizes:
        pubs, msgs, sigs = _make_sigs(n)
        triples = list(zip(pubs, msgs, sigs))
        for _ in range(reps):
            out = v.verify_batch(triples)
            assert bool(out.all()), "host verify must pass on valid sigs"


def drive_verify_device(sizes, reps) -> None:
    from tendermint_tpu.services.verifier import DeviceBatchVerifier

    v = DeviceBatchVerifier(min_device_batch=1)
    for n in sizes:
        pubs, msgs, sigs = _make_sigs(n)
        triples = list(zip(pubs, msgs, sigs))
        for _ in range(reps):
            v.verify_batch(triples)


def drive_verify_tables(n_vals: int, stack: int, reps: int) -> None:
    from tendermint_tpu.services.verifier import TableBatchVerifier

    v = TableBatchVerifier(min_device_batch=1)
    pubs, msgs, sigs = _make_sigs(n_vals)
    commits = [(list(msgs), list(sigs))] * stack
    for _ in range(reps):
        v.verify_commits(pubs, commits)


def drive_hash(sizes, reps, backend: str) -> None:
    from tendermint_tpu.services.hasher import TreeHasher

    h = TreeHasher(backend=backend, min_device_leaves=2)
    for n in sizes:
        items = [b"leaf-%d" % i for i in range(n)]
        for _ in range(reps):
            h.root_from_items(items)


def drive_statesync(payload_kb: int, chunk_size: int, reps: int) -> None:
    """Snapshot take + full chunk-set verification through the service
    seam — fills tendermint_statesync_snapshot_seconds /
    _chunk_verify_seconds exactly as a serving/restoring node would."""
    from tendermint_tpu.db.kv import MemDB
    from tendermint_tpu.services.hasher import TreeHasher
    from tendermint_tpu.state.state import make_genesis_state
    from tendermint_tpu.statesync.snapshot import SnapshotStore, verify_chunks
    from tendermint_tpu.testing.nemesis import make_genesis

    genesis, _ = make_genesis(4, chain_id="bench-statesync")
    hasher = TreeHasher(backend="host")
    app_state = os.urandom(payload_kb * 1024)
    for _ in range(reps):
        st = make_genesis_state(MemDB(), genesis)
        st.last_block_height = 5
        st.app_hash = b"\xab" * 20
        store = SnapshotStore(MemDB(), hasher=hasher, chunk_size=chunk_size)
        m = store.take(st, app_state)
        chunks = [store.load_chunk(m.height, m.format, i) for i in range(m.chunks)]
        verify_chunks(m, chunks, hasher)


def statesync_summary() -> dict | None:
    n_snap, t_snap, snap_p50, snap_p99 = _histo(
        "tendermint_statesync_snapshot_seconds"
    )
    n_ver, t_ver, ver_p50, ver_p99 = _histo(
        "tendermint_statesync_chunk_verify_seconds"
    )
    if n_snap == 0 and n_ver == 0:
        return None
    out = {}
    if n_snap:
        out["snapshot"] = {
            "count": n_snap,
            "p50_ms": round(snap_p50 * 1e3, 3),
            "p99_ms": round(snap_p99 * 1e3, 3),
        }
    if n_ver:
        out["chunk_verify"] = {
            "count": n_ver,
            "p50_ms": round(ver_p50 * 1e3, 3),
            "p99_ms": round(ver_p99 * 1e3, 3),
        }
    return out


def drive_wal(n_records: int) -> None:
    from tendermint_tpu.consensus.wal import WAL, EndHeightMessage

    with tempfile.TemporaryDirectory(prefix="hotpath-wal-") as d:
        wal = WAL(os.path.join(d, "cs.wal"))
        for i in range(n_records):
            wal.save(EndHeightMessage(i))
        wal.close()


def _histo(name: str, **labels):
    """(count, sum, p50, p99) of an exported histogram series."""
    from tendermint_tpu.telemetry import REGISTRY

    fam = REGISTRY.get(name)
    if fam is None:
        return 0, 0.0, None, None
    child = fam.labels(**labels) if fam.labelnames else fam._child0()
    snap = child.value
    if snap["count"] == 0:
        return 0, 0.0, None, None
    return (
        snap["count"],
        snap["sum"],
        child.quantile(0.5),
        child.quantile(0.99),
    )


def backend_summary(backend: str) -> dict | None:
    n_calls, t_total, p50, p99 = _histo(
        "tendermint_verify_seconds", backend=backend
    )
    n_sigs, _, _, _ = _histo("tendermint_verify_batch_size", backend=backend)
    sig_total = _sum_of("tendermint_verify_batch_size", backend=backend)
    if n_calls == 0 or t_total <= 0:
        return None
    return {
        "calls": n_calls,
        "signatures": sig_total,
        "verifies_per_s": round(sig_total / t_total, 1),
        "p50_ms": round(p50 * 1e3, 3),
        "p99_ms": round(p99 * 1e3, 3),
    }


def hash_summary(backend: str) -> dict | None:
    n_calls, t_total, p50, p99 = _histo("tendermint_hash_seconds", backend=backend)
    leaves = _sum_of("tendermint_hash_batch_leaves", backend=backend)
    if n_calls == 0 or t_total <= 0:
        return None
    return {
        "calls": n_calls,
        "leaves": leaves,
        "leaves_per_s": round(leaves / t_total, 1),
        "p50_ms": round(p50 * 1e3, 3),
        "p99_ms": round(p99 * 1e3, 3),
    }


def _sum_of(name: str, **labels) -> float:
    _, total, _, _ = _histo(name, **labels)
    return total


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="BENCH_hotpath.json")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument(
        "--sizes", default="64,256,1024", help="comma-separated batch sizes"
    )
    ap.add_argument(
        "--wal-records", type=int, default=256, dest="wal_records"
    )
    ap.add_argument(
        "--statesync-kb",
        type=int,
        default=256,
        dest="statesync_kb",
        help="snapshot payload size driven through take+verify (0 skips)",
    )
    ap.add_argument(
        "--no-device",
        action="store_true",
        help="skip device backends even on TPU",
    )
    args = ap.parse_args(argv)
    sizes = [int(s) for s in args.sizes.split(",") if s]

    import jax

    on_device = jax.default_backend() != "cpu" and not args.no_device
    t0 = time.time()
    sys.stderr.write(f"driving host verify {sizes} x{args.reps}...\n")
    drive_verify_host(sizes, args.reps)
    sys.stderr.write(f"driving host merkle {sizes} x{args.reps}...\n")
    drive_hash(sizes, args.reps, "host")
    sys.stderr.write(f"driving WAL fsync x{args.wal_records}...\n")
    drive_wal(args.wal_records)
    if args.statesync_kb > 0:
        sys.stderr.write(
            f"driving statesync snapshot+verify {args.statesync_kb}KB x{args.reps}...\n"
        )
        drive_statesync(args.statesync_kb, chunk_size=16 * 1024, reps=args.reps)
    if on_device:
        sys.stderr.write("driving device verify/tables/merkle...\n")
        drive_verify_device(sizes, args.reps)
        drive_verify_tables(n_vals=max(sizes), stack=8, reps=args.reps)
        drive_hash(sizes, args.reps, "device")

    wal_count, wal_sum, wal_p50, wal_p99 = _histo("tendermint_wal_fsync_seconds")
    detail = {
        "wall_s": round(time.time() - t0, 2),
        "backend": jax.default_backend(),
        "verify": {
            b: s
            for b in ("host", "device", "tables")
            if (s := backend_summary(b)) is not None
        },
        "hash": {
            b: s
            for b in ("host", "device")
            if (s := hash_summary(b)) is not None
        },
        "statesync": statesync_summary(),
        "wal_fsync": {
            "count": wal_count,
            "fsyncs_per_s": round(wal_count / wal_sum, 1) if wal_sum else None,
            "p50_ms": round(wal_p50 * 1e3, 3) if wal_p50 is not None else None,
            "p99_ms": round(wal_p99 * 1e3, 3) if wal_p99 is not None else None,
        },
    }
    # headline: the fastest verify backend exercised this run
    best_backend, best = max(
        detail["verify"].items(), key=lambda kv: kv[1]["verifies_per_s"]
    )
    out = {
        "metric": f"hotpath_{best_backend}_verifies_per_s",
        "value": best["verifies_per_s"],
        "unit": "verifies/s",
        "detail": detail,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
