"""Hot-path bench driven THROUGH the telemetry registry.

Exercises the verify/hash service backends and the consensus-WAL fsync
path, then derives `BENCH_hotpath.json` from the same histograms the
node exports on `GET /metrics` — so bench numbers and production
telemetry can never disagree about what was measured.

Backend selection is automatic: on CPU (`JAX_PLATFORMS=cpu`, the CI
shape) only the host backends run — no XLA kernel compiles, finishes in
seconds. On a TPU backend the device verifier, the valset-table
verifier, and the device Merkle tree run too (first run pays compiles
unless the persistent executable cache is warm).

    JAX_PLATFORMS=cpu python tools/bench_hotpath.py          # CI shape
    python tools/bench_hotpath.py --out BENCH_hotpath.json   # device shape

Output: one JSON line on stdout + the JSON file (default
`BENCH_hotpath.json` in the CWD).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.utils.jax_cache import enable_persistent_cache

enable_persistent_cache()


def _make_sigs(n: int):
    from tendermint_tpu.crypto.keys import gen_priv_key

    privs = [gen_priv_key(bytes([i % 256]) * 32) for i in range(min(64, n))]
    msgs = [
        b'{"chain_id":"hotpath","vote":{"height":7,"round":0,"index":%d}}' % i
        for i in range(n)
    ]
    sigs = [privs[i % len(privs)].sign(m) for i, m in enumerate(msgs)]
    pubs = [privs[i % len(privs)].pub_key.data for i in range(n)]
    return pubs, msgs, sigs


def drive_verify_host(sizes, reps) -> None:
    from tendermint_tpu.services.verifier import HostBatchVerifier

    v = HostBatchVerifier()
    for n in sizes:
        pubs, msgs, sigs = _make_sigs(n)
        triples = list(zip(pubs, msgs, sigs))
        for _ in range(reps):
            out = v.verify_batch(triples)
            assert bool(out.all()), "host verify must pass on valid sigs"


def drive_verify_device(sizes, reps) -> None:
    from tendermint_tpu.services.verifier import DeviceBatchVerifier

    v = DeviceBatchVerifier(min_device_batch=1)
    for n in sizes:
        pubs, msgs, sigs = _make_sigs(n)
        triples = list(zip(pubs, msgs, sigs))
        for _ in range(reps):
            v.verify_batch(triples)


def drive_verify_tables(n_vals: int, stack: int, reps: int) -> None:
    from tendermint_tpu.services.verifier import TableBatchVerifier

    v = TableBatchVerifier(min_device_batch=1)
    pubs, msgs, sigs = _make_sigs(n_vals)
    commits = [(list(msgs), list(sigs))] * stack
    for _ in range(reps):
        v.verify_commits(pubs, commits)


def drive_hash(sizes, reps, backend: str) -> None:
    from tendermint_tpu.services.hasher import TreeHasher

    h = TreeHasher(backend=backend, min_device_leaves=2)
    for n in sizes:
        items = [b"leaf-%d" % i for i in range(n)]
        for _ in range(reps):
            h.root_from_items(items)


def drive_statesync(payload_kb: int, chunk_size: int, reps: int) -> None:
    """Snapshot take + full chunk-set verification through the service
    seam — fills tendermint_statesync_snapshot_seconds /
    _chunk_verify_seconds exactly as a serving/restoring node would."""
    from tendermint_tpu.db.kv import MemDB
    from tendermint_tpu.services.hasher import TreeHasher
    from tendermint_tpu.state.state import make_genesis_state
    from tendermint_tpu.statesync.snapshot import SnapshotStore, verify_chunks
    from tendermint_tpu.testing.nemesis import make_genesis

    genesis, _ = make_genesis(4, chain_id="bench-statesync")
    hasher = TreeHasher(backend="host")
    app_state = os.urandom(payload_kb * 1024)
    for _ in range(reps):
        st = make_genesis_state(MemDB(), genesis)
        st.last_block_height = 5
        st.app_hash = b"\xab" * 20
        store = SnapshotStore(MemDB(), hasher=hasher, chunk_size=chunk_size)
        m = store.take(st, app_state)
        chunks = [store.load_chunk(m.height, m.format, i) for i in range(m.chunks)]
        verify_chunks(m, chunks, hasher)


def statesync_summary() -> dict | None:
    n_snap, t_snap, snap_p50, snap_p99 = _histo(
        "tendermint_statesync_snapshot_seconds"
    )
    n_ver, t_ver, ver_p50, ver_p99 = _histo(
        "tendermint_statesync_chunk_verify_seconds"
    )
    if n_snap == 0 and n_ver == 0:
        return None
    out = {}
    if n_snap:
        out["snapshot"] = {
            "count": n_snap,
            "p50_ms": round(snap_p50 * 1e3, 3),
            "p99_ms": round(snap_p99 * 1e3, 3),
        }
    if n_ver:
        out["chunk_verify"] = {
            "count": n_ver,
            "p50_ms": round(ver_p50 * 1e3, 3),
            "p99_ms": round(ver_p99 * 1e3, 3),
        }
    return out


def _build_chain(n_blocks: int, n_vals: int):
    """A real committed chain (blocks + quorum commits + genesis) via
    the testing chain machinery — what the fast-sync bench replays."""
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.client import local_client_creator
    from tendermint_tpu.db.kv import MemDB
    from tendermint_tpu.state import apply_block, make_genesis_state
    from tendermint_tpu.testing.nemesis import make_genesis
    from tendermint_tpu.types import BlockID, Commit, Txs
    from tendermint_tpu.types.block import Block
    from tendermint_tpu.types.vote import VOTE_TYPE_PRECOMMIT
    from tendermint_tpu.types.vote_set import VoteSet
    from tendermint_tpu.types.vote import Vote

    genesis, privs = make_genesis(n_vals, chain_id="bench-fastsync")
    state = make_genesis_state(MemDB(), genesis)
    state.save()
    conns = local_client_creator(KVStoreApp())()
    blocks, commits = [], []
    for _ in range(n_blocks):
        height = state.last_block_height + 1
        last_commit = commits[-1] if commits else Commit.empty()
        block = Block.make_block(
            height=height,
            chain_id=state.chain_id,
            txs=Txs([]),
            last_commit=last_commit,
            last_block_id=state.last_block_id,
            time=genesis.genesis_time + height * 1_000_000_000,
            validators_hash=state.validators.hash(),
            app_hash=state.app_hash,
        )
        part_set = block.make_part_set()
        block_id = BlockID(block.hash(), part_set.header)
        vote_set = VoteSet(
            state.chain_id, height, 0, VOTE_TYPE_PRECOMMIT, state.validators
        )
        for i, priv in enumerate(privs):
            vote = Vote(
                validator_address=priv.address,
                validator_index=i,
                height=height,
                round=0,
                timestamp=genesis.genesis_time + height * 1_000_000_000,
                type=VOTE_TYPE_PRECOMMIT,
                block_id=block_id,
            )
            vote_set.add_vote(priv.sign_vote(state.chain_id, vote))
        apply_block(state, block, part_set.header, conns.consensus)
        blocks.append(block)
        commits.append(vote_set.make_commit())
    return genesis, blocks


class _LaunchLatencyVerifier:
    """CPU stand-in for the device verifier's dispatch shape: real host
    crypto preceded by the measured fixed launch cost (~86 ms through
    the axon tunnel, docs/PLATFORM_NOTES.md) spent OFF the GIL — which
    is exactly what an in-flight kernel looks like to the host. Lets the
    checked-in CPU seed measure what the pipeline hides; on a TPU
    backend the bench uses the real table verifier instead."""

    def __init__(self, launch_s: float):
        from tendermint_tpu.services.verifier import HostBatchVerifier

        self._host = HostBatchVerifier()
        self._launch_s = launch_s

    def verify_batch(self, triples):
        time.sleep(self._launch_s)
        return self._host.verify_batch(triples)

    # async seam: whole call runs on the DispatchQueue worker
    launch_verify_batch = verify_batch

    def finalize_verify_batch(self, launched):
        return launched

    def verify_batch_async(self, triples, queue=None):
        from tendermint_tpu.services.dispatch import default_dispatch_queue

        q = queue if queue is not None else default_dispatch_queue()
        return q.submit(lambda: self.verify_batch(triples), kind="verify")


def _overlap_stats():
    """(count, sum) of the fastsync queue's overlap-ratio histogram."""
    n, total, _, _ = _histo("tendermint_dispatch_overlap_ratio", queue="fastsync")
    return n, total


def drive_fastsync_pipeline(
    n_blocks: int, n_vals: int, launch_ms: float, on_device: bool
) -> dict:
    """Replay one committed chain through the REAL
    `BlockchainReactor._try_sync` twice — pipeline depth 1 (the
    synchronous verify->apply baseline) vs the default overlapped depth
    — and report blocks/s plus the telemetry-measured overlap ratio."""
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.client import local_client_creator
    from tendermint_tpu.blockchain.reactor import PIPELINE_DEPTH, BlockchainReactor
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.db.kv import MemDB
    from tendermint_tpu.state import make_genesis_state

    genesis, blocks = _build_chain(n_blocks, n_vals)
    if on_device:
        from tendermint_tpu.services.resilient import ResilientVerifier
        from tendermint_tpu.services.verifier import TableBatchVerifier

        verifier = ResilientVerifier(TableBatchVerifier(min_device_batch=1))
        launch_ms = 0.0  # real launches, no emulation
    else:
        verifier = _LaunchLatencyVerifier(launch_ms / 1e3)

    def run(depth: int) -> float:
        state = make_genesis_state(MemDB(), genesis)
        state.save()
        store = BlockStore(MemDB())
        conns = local_client_creator(KVStoreApp())()
        reactor = BlockchainReactor(
            state=state,
            store=store,
            app_conn=conns.consensus,
            fast_sync=True,
            verifier=verifier,
            pipeline_depth=depth,
        )
        reactor.pool.set_peer_height("bench", len(blocks))
        for h, b in enumerate(blocks, start=1):
            reactor.pool._blocks[h] = (b, "bench")
        t0 = time.perf_counter()
        reactor._try_sync()
        dt = time.perf_counter() - t0
        assert store.height == len(blocks) - 1, (
            f"bench sync stalled at {store.height}"
        )
        return (len(blocks) - 1) / dt

    depth = max(2, PIPELINE_DEPTH)
    sync_bps = run(1)
    ov_n0, ov_s0 = _overlap_stats()
    pipelined_bps = run(depth)
    ov_n1, ov_s1 = _overlap_stats()
    overlap = (ov_s1 - ov_s0) / (ov_n1 - ov_n0) if ov_n1 > ov_n0 else 0.0
    return {
        "blocks": n_blocks,
        "validators": n_vals,
        "pipeline_depth": depth,
        "launch_overhead_ms": launch_ms,
        "emulated_launch": not on_device,
        "sync_blocks_per_s": round(sync_bps, 1),
        "pipelined_blocks_per_s": round(pipelined_bps, 1),
        "speedup": round(pipelined_bps / sync_bps, 3),
        "overlap_ratio_mean": round(overlap, 3),
    }


def _salted_sigs(n: int, salt: bytes):
    """Like `_make_sigs` but with per-call-unique messages, so replay
    loops control exactly which triples repeat."""
    from tendermint_tpu.crypto.keys import gen_priv_key

    privs = [gen_priv_key(bytes([i % 256]) * 32) for i in range(min(64, n))]
    msgs = [
        b'{"chain_id":"hotpath","salt":"%s","vote":{"index":%d}}' % (salt, i)
        for i in range(n)
    ]
    sigs = [privs[i % len(privs)].sign(m) for i, m in enumerate(msgs)]
    pubs = [privs[i % len(privs)].pub_key.data for i in range(n)]
    return list(zip(pubs, msgs, sigs))


def drive_dedup_steady_state(heights: int, n_vals: int, launch_ms: float) -> dict:
    """Gossip-then-commit height replay through the dedup cache: each
    height's votes are verified once on gossip arrival and again when
    the commit seals the block — the exact redundancy the cache exists
    to remove. Cache-off pays the emulated launch twice per height
    (same CPU method as `fastsync_pipeline`); cache-on serves the
    commit pass from proven triples."""
    from tendermint_tpu.services.batcher import CoalescingVerifier

    height_triples = [
        _salted_sigs(n_vals, b"h%d" % h) for h in range(heights)
    ]

    def run(cache_size: int) -> float:
        v = CoalescingVerifier(
            _LaunchLatencyVerifier(launch_ms / 1e3),
            cache_size=cache_size,
            window_s=0.001,
        )
        try:
            total = 0
            t0 = time.perf_counter()
            for triples in height_triples:
                assert bool(v.verify_batch(triples).all())  # gossip drain
                assert bool(v.verify_batch(triples).all())  # commit seal
                total += 2 * len(triples)
            return total / (time.perf_counter() - t0)
        finally:
            v.close()

    def _cache_hits() -> float:
        from tendermint_tpu.telemetry import REGISTRY

        return REGISTRY.counter_value("tendermint_verify_cache_hits_total")

    off_vps = run(cache_size=0)
    h0 = _cache_hits()
    on_vps = run(cache_size=65536)
    return {
        "heights": heights,
        "validators": n_vals,
        "launch_overhead_ms": launch_ms,
        "emulated_launch": True,
        "cache_off_verifies_per_s": round(off_vps, 1),
        "cache_on_verifies_per_s": round(on_vps, 1),
        "speedup": round(on_vps / off_vps, 3),
        "cache_hits": int(_cache_hits() - h0),
    }


def drive_tracing_overhead(heights: int, n_vals: int, launch_ms: float) -> dict:
    """Bench guard for the distributed tracer: verifies/s on the
    dedup_steady_state replay with head-based sampling at the
    production default (1-in-64) must sit within 3% of tracing-off.
    The traced run exercises the real costs: the ambient thread-local
    read at every coalescer submit, plus flush/launch spans and flight
    events for the sampled heights."""
    from tendermint_tpu.services.batcher import CoalescingVerifier
    from tendermint_tpu.telemetry import tracectx as _tc

    height_triples = [
        _salted_sigs(n_vals, b"trace-h%d" % h) for h in range(heights)
    ]

    def run(sample: int) -> float:
        prev = os.environ.get(_tc.SAMPLE_ENV)
        os.environ[_tc.SAMPLE_ENV] = str(sample)
        v = CoalescingVerifier(
            _LaunchLatencyVerifier(launch_ms / 1e3),
            cache_size=65536,
            window_s=0.001,
        )
        try:
            total = 0
            t0 = time.perf_counter()
            for triples in height_triples:
                # the admission edge: head-sampled mint, then the whole
                # height's verify work runs with the context ambient
                # (exactly the consensus vote-drain shape)
                with _tc.use(_tc.mint("bench") if sample else None):
                    for consumer in ("consensus", "fastsync"):
                        assert bool(
                            v.verify_batch_async(triples, consumer=consumer)
                            .result(timeout=60)
                            .all()
                        )
                total += 2 * len(triples)
            return total / (time.perf_counter() - t0)
        finally:
            v.close()
            if prev is None:
                os.environ.pop(_tc.SAMPLE_ENV, None)
            else:
                os.environ[_tc.SAMPLE_ENV] = prev

    run(0)  # warmup: host-crypto/thread spin-up would bias the first run
    off_vps = run(0)
    on_vps = run(64)
    overhead_pct = 100.0 * (1.0 - on_vps / off_vps)
    return {
        "heights": heights,
        "validators": n_vals,
        "launch_overhead_ms": launch_ms,
        "emulated_launch": True,
        "sample_rate": 64,
        "tracing_off_verifies_per_s": round(off_vps, 1),
        "tracing_on_verifies_per_s": round(on_vps, 1),
        "overhead_pct": round(overhead_pct, 2),
        "within_3pct": overhead_pct <= 3.0,
    }


def drive_profiler_overhead(heights: int, n_vals: int, launch_ms: float) -> dict:
    """Bench guard for the contention observatory (PR 12): verifies/s
    on the dedup_steady_state replay with the profiler OFF vs armed at
    the default 29 Hz WITH ranked-lock contention timing — the
    always-on-capable configuration — must sit within 3% of off. The
    armed run pays the real costs: the sampler walking every thread's
    stack ~29x/s, the per-acquire perf_counter pair + stat update on
    every instrumented lock (cache shards, coalescer window, dispatch
    locks), and the wait/hold histogram observes."""
    from tendermint_tpu.services.batcher import CoalescingVerifier
    from tendermint_tpu.telemetry.profiler import PROFILER
    from tendermint_tpu.utils import lockrank

    # locks must be *instrumentable* for the armed half: make this
    # process timing-capable before the verifier stack constructs them
    # (no-op under the tier-1 suite, which runs with the sanitizer on)
    os.environ.setdefault("TENDERMINT_TPU_PROFILE_HZ", "0")

    height_triples = [
        _salted_sigs(n_vals, b"prof-h%d" % h) for h in range(heights)
    ]
    replays = 3  # long enough for 29 Hz to land real samples

    def run() -> float:
        v = CoalescingVerifier(
            _LaunchLatencyVerifier(launch_ms / 1e3),
            cache_size=65536,
            window_s=0.001,
        )
        try:
            total = 0
            t0 = time.perf_counter()
            for _ in range(replays):
                for triples in height_triples:
                    for consumer in ("consensus", "fastsync"):
                        assert bool(
                            v.verify_batch_async(triples, consumer=consumer)
                            .result(timeout=60)
                            .all()
                        )
                    total += 2 * len(triples)
            return total / (time.perf_counter() - t0)
        finally:
            v.close()

    run()  # warmup: thread spin-up / memo fills excluded
    off_vps = run()
    PROFILER.reset()
    lockrank.reset_contention()
    PROFILER.start(hz=29)
    try:
        on_vps = run()
    finally:
        PROFILER.stop()
    snap = PROFILER.snapshot(top_stacks=5)
    locks = lockrank.contention_snapshot(top=3)["locks"]
    overhead_pct = 100.0 * (1.0 - on_vps / off_vps)
    return {
        "heights": heights,
        "validators": n_vals,
        "launch_overhead_ms": launch_ms,
        "emulated_launch": True,
        "profile_hz": 29,
        "lock_timing": True,
        "profiler_off_verifies_per_s": round(off_vps, 1),
        "profiler_on_verifies_per_s": round(on_vps, 1),
        "overhead_pct": round(overhead_pct, 2),
        "within_3pct": overhead_pct <= 3.0,
        # proof the armed half measured something real, not a no-op
        "samples": snap["samples"],
        "subsystems_seen": sorted(snap["subsystems"]),
        "top_contended_lock": locks[0]["lock"] if locks else None,
    }


def drive_device_efficiency(heights: int, n_vals: int, launch_ms: float) -> dict:
    """`device_efficiency` section (the device observatory, PR 13) —
    two halves:

    * **ledger overhead guard**: the dedup_steady_state coalescer
      replay with `TENDERMINT_TPU_LAUNCHLOG=0` vs on; recording one
      structured record per launch must stay within 3% of off.
    * **occupancy/waste accounting**: real mesh-geometry launches
      through a host-executor `MeshManager` over the virtual CPU mesh
      (the per-chip power-of-two bucket padding of the REAL sharded
      path, no XLA compile), at batch sizes chosen to land on and off
      bucket boundaries — occupancy %, padding waste %, and compile
      amortization read back from the LaunchLedger records the bench
      just produced. CPU seed: compile counters stay zero (the host
      executor compiles nothing); a real-silicon reseed fills them.
    """
    import jax

    from tendermint_tpu.parallel.mesh import MeshManager
    from tendermint_tpu.services.batcher import CoalescingVerifier
    from tendermint_tpu.services.verifier import ShardedBatchVerifier
    from tendermint_tpu.telemetry import launchlog

    height_triples = [
        _salted_sigs(n_vals, b"dev-h%d" % h) for h in range(heights)
    ]

    def run() -> float:
        v = CoalescingVerifier(
            _LaunchLatencyVerifier(launch_ms / 1e3),
            cache_size=65536,
            window_s=0.001,
        )
        try:
            total = 0
            t0 = time.perf_counter()
            for triples in height_triples:
                for consumer in ("consensus", "fastsync"):
                    assert bool(
                        v.verify_batch_async(triples, consumer=consumer)
                        .result(timeout=60)
                        .all()
                    )
                total += 2 * len(triples)
            return total / (time.perf_counter() - t0)
        finally:
            v.close()

    prev = os.environ.get("TENDERMINT_TPU_LAUNCHLOG")
    run()  # warmup (thread spin-up excluded from both halves)
    try:
        os.environ["TENDERMINT_TPU_LAUNCHLOG"] = "0"
        off_vps = run()
        os.environ["TENDERMINT_TPU_LAUNCHLOG"] = "1"
        t_mark = time.time()
        on_vps = run()
    finally:
        if prev is None:
            os.environ.pop("TENDERMINT_TPU_LAUNCHLOG", None)
        else:
            os.environ["TENDERMINT_TPU_LAUNCHLOG"] = prev
    overhead_pct = 100.0 * (1.0 - on_vps / off_vps)
    ledger_records = [
        r for r in launchlog.LAUNCHLOG.recent() if r.get("t", 0) >= t_mark
    ]

    # occupancy half: the REAL mesh pad geometry (per-chip bucket *
    # width) over the virtual-device mesh, host executor = no compiles
    mgr = MeshManager(
        devices=list(jax.devices())[: min(8, len(jax.devices()))],
        executor="host",
    )
    mesh_v = ShardedBatchVerifier(mesh=mgr, min_device_batch=1)
    t_mark2 = time.time()
    sizes = (n_vals, n_vals + 1, 8 * mgr.n_active)  # on/off bucket edges
    for size in sizes:
        triples = _salted_sigs(size, b"dev-occ-%d" % size)
        assert bool(mesh_v.verify_batch(triples).all())
    mesh_records = [
        r
        for r in launchlog.LAUNCHLOG.recent(kind="verify")
        if r.get("t", 0) >= t_mark2 and r.get("mesh_width")
    ]
    summary = launchlog.summarize(mesh_records).get("verify") or {}

    from tendermint_tpu.telemetry import REGISTRY

    hits = REGISTRY.counter_value("tendermint_mesh_compile_total", result="hit")
    misses = REGISTRY.counter_value(
        "tendermint_mesh_compile_total", result="miss"
    )
    return {
        "heights": heights,
        "validators": n_vals,
        "launch_overhead_ms": launch_ms,
        "emulated_launch": True,
        "ledger_off_verifies_per_s": round(off_vps, 1),
        "ledger_on_verifies_per_s": round(on_vps, 1),
        "overhead_pct": round(overhead_pct, 2),
        "within_3pct": overhead_pct <= 3.0,
        # proof the on half actually recorded (a silently-disabled
        # ledger would pass the overhead guard trivially)
        "records": len(ledger_records),
        "mesh_width": mgr.n_active,
        "mesh_launch_sizes": list(sizes),
        "mesh_launches": len(mesh_records),
        "occupancy_pct": summary.get("occupancy_pct"),
        "padding_waste_pct": summary.get("padding_waste_pct"),
        "compile_amortization": {
            "hits": int(hits),
            "misses": int(misses),
            "hit_rate": round(hits / (hits + misses), 3)
            if hits + misses
            else None,
        },
    }


def _build_fullcommit_chain(heights: int, n_vals: int, rotate_every: int):
    """FullCommits for heights 1..N with one validator replaced every
    `rotate_every` heights (sliding window over deterministic keys), so
    a long jump's old-set overlap decays linearly — the read-path walk
    benches need BOTH regimes: jumps the 2/3 dynamic rule rejects and
    the 1/3 skip rule still accepts."""
    from tendermint_tpu.certifiers.certifier import FullCommit
    from tendermint_tpu.certifiers.provider import MemProvider
    from tendermint_tpu.crypto import PrivKey
    from tendermint_tpu.types import (
        VOTE_TYPE_PRECOMMIT,
        BlockID,
        PartSetHeader,
        PrivValidator,
        Validator,
        ValidatorSet,
        Vote,
        VoteSet,
    )
    from tendermint_tpu.types.block import Header

    chain_id = "reads-bench"
    privs_by_id: dict[int, object] = {}

    def priv(i: int):
        if i not in privs_by_id:
            privs_by_id[i] = PrivValidator(PrivKey(i.to_bytes(32, "little")))
        return privs_by_id[i]

    source = MemProvider()
    fcs = {}
    for h in range(1, heights + 1):
        rot = (h - 1) // max(1, rotate_every)
        privs = [priv(1 + rot + k) for k in range(n_vals)]
        vs = ValidatorSet(
            [
                Validator(address=p.address, pub_key=p.pub_key, voting_power=10)
                for p in privs
            ]
        )
        header = Header(
            chain_id=chain_id,
            height=h,
            time=h * 1_000_000_000,
            num_txs=0,
            last_block_id=BlockID.zero(),
            validators_hash=vs.hash(),
            app_hash=b"app",
        )
        block_id = BlockID(
            header.hash(), PartSetHeader(total=1, hash=header.hash()[:20])
        )
        by_addr = {p.address: p for p in privs}
        vote_set = VoteSet(chain_id, h, 0, VOTE_TYPE_PRECOMMIT, vs)
        for idx, val in enumerate(vs.validators):
            p = by_addr[val.address]
            vote = Vote(
                validator_address=p.address,
                validator_index=idx,
                height=h,
                round=0,
                timestamp=h,
                type=VOTE_TYPE_PRECOMMIT,
                block_id=block_id,
            )
            vote_set.add_vote(p.sign_vote(chain_id, vote))
        fc = FullCommit(
            header=header, commit=vote_set.make_commit(), validators=vs
        )
        source.store_commit(fc)
        fcs[h] = fc
    return chain_id, source, fcs


class _CountingVerifier:
    """Counts launches (submissions) + verifies (triples) flowing
    through an inner consumer-tagged verifier — walk-cost attribution
    for the reads bench."""

    accepts_consumer = True

    def __init__(self, inner):
        self.inner = inner
        self.verifies = 0
        self.launches = 0

    def reset(self):
        self.verifies = 0
        self.launches = 0

    def verify_batch(self, triples):
        self.verifies += len(triples)
        self.launches += 1
        return self.inner.verify_batch(triples)

    def verify_batch_async(self, triples, queue=None, consumer: str = "default"):
        self.verifies += len(triples)
        self.launches += 1
        return self.inner.verify_batch_async(triples, consumer=consumer)


def drive_reads(
    heights: int, n_vals: int, rotate_every: int, launch_ms: float
) -> dict:
    """The read path A/B (ROADMAP item 1): a fresh light client
    verifying to the chain tip through the sequential
    `InquiringCertifier` walk vs the batched-bisection
    `BisectingCertifier`, both over the coalescing stack with the
    emulated per-launch cost — plus the serving half (certified
    FullCommit lookups + encodes per second). Dedup cache OFF so every
    walk pays its honest verification cost (a new client shares no
    proven triples)."""
    from tendermint_tpu.certifiers.certifier import InquiringCertifier
    from tendermint_tpu.certifiers.provider import MemProvider
    from tendermint_tpu.db.fullcommit import FullCommitStore
    from tendermint_tpu.db.kv import MemDB
    from tendermint_tpu.lightclient import BisectingCertifier, CertifiedCommitCache
    from tendermint_tpu.services.batcher import CoalescingVerifier

    chain_id, source, fcs = _build_fullcommit_chain(heights, n_vals, rotate_every)
    target = fcs[heights]

    def run(mode: str, walks: int) -> dict:
        # _DeviceShapeVerifier: emulated fixed launch + tiny per-sig
        # marginal with host spot checks — the device cost shape, so the
        # A/B measures launches saved, not host-crypto throughput
        verifier = _CountingVerifier(
            CoalescingVerifier(
                _DeviceShapeVerifier(launch_ms / 1e3),
                cache_size=0,
                window_s=0.001,
            )
        )
        try:
            t0 = time.perf_counter()
            for _ in range(walks):
                if mode == "bisect":
                    cert = BisectingCertifier(
                        chain_id,
                        seed=fcs[1],
                        trusted=MemProvider(),
                        source=source,
                        verifier=verifier,
                    )
                    cert.verify_to_height(heights)
                    assert cert.last_height == heights
                else:
                    cert = InquiringCertifier(
                        chain_id,
                        fcs[1],
                        MemProvider(),
                        source,
                        verifier=verifier,
                    )
                    cert.certify(target)
            elapsed = time.perf_counter() - t0
        finally:
            verifier.inner.close()
        return {
            "walks": walks,
            "walks_per_s": round(walks / elapsed, 3),
            "verifies_per_walk": round(verifier.verifies / walks, 1),
            "launches_per_walk": round(verifier.launches / walks, 1),
        }

    sequential = run("sequential", walks=2)
    bisect = run("bisect", walks=4)

    # serving half: certified-cache lookups + wire encodes (hot-height
    # skew — what a replica's proof-serving hot loop does per query)
    cache = CertifiedCommitCache(store=FullCommitStore(MemDB()))
    for fc in fcs.values():
        cache.put_certified(fc)
    import random as _random

    rng = _random.Random(5)
    n_queries = 2000
    t0 = time.perf_counter()
    for _ in range(n_queries):
        h = (
            heights - rng.randrange(8)
            if rng.random() < 0.7
            else rng.randrange(1, heights + 1)
        )
        fc = cache.get_exact(max(1, h))
        assert fc is not None
        fc.encode()
    proofs_per_s = n_queries / (time.perf_counter() - t0)

    return {
        "heights": heights,
        "validators": n_vals,
        "rotate_every": rotate_every,
        "launch_overhead_ms": launch_ms,
        "emulated_launch": True,
        "sequential": sequential,
        "bisect": bisect,
        "bisect_speedup": round(
            bisect["walks_per_s"] / sequential["walks_per_s"], 3
        ),
        "verify_reduction": round(
            sequential["verifies_per_walk"] / max(1.0, bisect["verifies_per_walk"]),
            3,
        ),
        "proofs_served_per_s": round(proofs_per_s, 1),
    }


def drive_coalesce_multiconsumer(rounds: int, batch: int, launch_ms: float) -> dict:
    """All four verify consumers live at once: consensus, fast-sync,
    statesync, and rpc threads submit concurrent async batches through
    one coalescer; the coalesce factor (requests merged per launch) is
    read back from the telemetry the coalescer exports."""
    import threading

    from tendermint_tpu.services.batcher import CoalescingVerifier

    consumers = ("consensus", "fastsync", "statesync", "rpc")
    pre = {
        tag: [
            _salted_sigs(batch, b"%s-r%d" % (tag.encode(), r))
            for r in range(rounds)
        ]
        for tag in consumers
    }
    v = CoalescingVerifier(
        _LaunchLatencyVerifier(launch_ms / 1e3), cache_size=0, window_s=0.005
    )
    n0, s0, _, _ = _histo("tendermint_batcher_coalesce_factor")
    gate = threading.Barrier(len(consumers))
    errors: list = []

    def worker(tag: str) -> None:
        try:
            for triples in pre[tag]:
                gate.wait()  # align the four consumers' submit instants
                if not v.verify_batch_async(triples, consumer=tag).result(
                    timeout=60
                ).all():
                    errors.append(f"{tag}: bad verdict")
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(f"{tag}: {e}")

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(tag,)) for tag in consumers]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    v.close()
    assert not errors, errors
    n1, s1, _, _ = _histo("tendermint_batcher_coalesce_factor")
    launches = n1 - n0
    factor = (s1 - s0) / launches if launches else 0.0
    total = len(consumers) * rounds * batch
    return {
        "consumers": list(consumers),
        "rounds": rounds,
        "batch_per_request": batch,
        "launch_overhead_ms": launch_ms,
        "emulated_launch": True,
        "verifies_per_s": round(total / dt, 1),
        "coalesced_launches": int(launches),
        "requests": len(consumers) * rounds,
        "coalesce_factor_mean": round(factor, 3),
    }


class _DeviceShapeVerifier:
    """CPU stand-in for the device verifier's INGRESS shape: a fixed
    launch cost plus the measured device marginal per-signature cost,
    both spent OFF the GIL (exactly what an in-flight kernel looks like
    to the host), with a real host-crypto spot check of a sample so the
    emulation can't return verdicts for garbage. The ingress comparison
    is architectural — launch-per-tx vs launch-per-window — and the
    launch is the term the device actually charges (~86 ms through the
    axon tunnel; per-sig marginal ~0.7 µs at the PR 6 ~1.45M/s table
    rate). Flagged `emulated_launch` like every CPU-seed section."""

    accepts_consumer = True

    def __init__(self, launch_s: float, per_sig_s: float = 2e-6, sample: int = 2):
        from tendermint_tpu.services.verifier import HostBatchVerifier

        self._host = HostBatchVerifier()
        self._launch_s = launch_s
        self._per_sig_s = per_sig_s
        self._sample = sample

    def verify_batch(self, triples):
        import numpy as np

        time.sleep(self._launch_s + self._per_sig_s * len(triples))
        n = len(triples)
        idx = list(range(0, n, max(1, n // self._sample)))[: self._sample]
        spot = self._host.verify_batch([triples[i] for i in idx])
        return np.full(n, bool(spot.all()), dtype=bool)

    launch_verify_batch = verify_batch

    def finalize_verify_batch(self, launched):
        return launched

    def verify_batch_async(self, triples, queue=None, consumer: str = "default"):
        from tendermint_tpu.services.dispatch import default_dispatch_queue

        q = queue if queue is not None else default_dispatch_queue()
        return q.submit(lambda: self.verify_batch(triples), kind="verify")


def drive_mempool_ingress(
    n_txs: int, threads: int, launch_ms: float, lanes_list=(1, 4, 8)
) -> dict:
    """`mempool_ingress` section: signed CheckTx traffic through the
    REAL admission paths — legacy one-at-a-time (launch per tx, the
    pre-ingress shape) vs the batched+sharded pipeline (launch per
    verify window through the coalescer) — at 1/4/8 lanes, with p99
    admission latency read from the same histogram a node exports."""
    import threading

    from tendermint_tpu.abci.apps import NilApp
    from tendermint_tpu.abci.client import local_client_creator
    from tendermint_tpu.crypto.keys import gen_priv_key
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.mempool.ingress import make_signed_tx
    from tendermint_tpu.services.batcher import CoalescingVerifier

    privs = [gen_priv_key(bytes([i % 256]) * 32) for i in range(16)]
    sys.stderr.write(f"  pre-signing {n_txs} txs...\n")
    tx_sets: dict = {}

    def txs_for(run_key: str) -> list[bytes]:
        # distinct payloads per run so dup caches never cross runs
        if run_key not in tx_sets:
            tx_sets[run_key] = [
                make_signed_tx(
                    privs[i % len(privs)], b"%s/k%d=%d" % (run_key.encode(), i, i)
                )
                for i in range(n_txs)
            ]
        return tx_sets[run_key]

    def run(run_key: str, batch_on: bool, lanes: int) -> dict:
        conns = local_client_creator(NilApp())()
        verifier = CoalescingVerifier(
            _DeviceShapeVerifier(launch_ms / 1e3),
            cache_size=0,
            window_s=0.001,
        )
        mp = Mempool(
            conns.mempool,
            cache_size=4 * n_txs,
            verifier=verifier,
            lanes=lanes,
            ingress_batch=batch_on,
        )
        txs = txs_for(run_key)
        n0, _, _, _ = _histo("tendermint_mempool_admission_seconds")
        errors: list = []
        lat: list[float] = []
        lat_lock = threading.Lock()
        done = threading.Event()

        def worker(k: int) -> None:
            # the RPC-broadcast / gossip-recv shape: non-blocking
            # submits, results via callback — intake threads never
            # stall on a window join, so windows grow with load
            try:
                for tx in txs[k::threads]:
                    t_sub = time.perf_counter()

                    def cb(res, t_sub=t_sub):
                        if not res.is_ok:
                            errors.append(res.log)
                        with lat_lock:
                            lat.append(time.perf_counter() - t_sub)
                            if len(lat) == n_txs:
                                done.set()

                    mp.check_tx_async(tx, cb)
            except Exception as e:  # pragma: no cover - surfaced below
                errors.append(repr(e))
                done.set()

        ts = [threading.Thread(target=worker, args=(k,)) for k in range(threads)]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert done.wait(timeout=120), "ingress admissions did not drain"
        dt = time.perf_counter() - t0
        assert not errors, errors[:3]
        assert mp.size() == n_txs
        mp.close()
        verifier.close()
        n1, _, _, _ = _histo("tendermint_mempool_admission_seconds")
        lat.sort()
        p50 = lat[len(lat) // 2]
        p99 = lat[min(len(lat) - 1, int(0.99 * (len(lat) - 1)))]
        return {
            "lanes": lanes,
            "batched": batch_on,
            "checktx_per_s": round(n_txs / dt, 1),
            "p50_admission_ms": round(p50 * 1e3, 3),
            "p99_admission_ms": round(p99 * 1e3, 3),
            # proof the exported histogram saw this run's admissions
            "admissions_observed": int(n1 - n0),
        }

    sys.stderr.write("  legacy one-at-a-time path...\n")
    legacy = run("legacy", batch_on=False, lanes=1)
    rows = []
    for lanes in lanes_list:
        sys.stderr.write(f"  batched ingress, {lanes} lanes...\n")
        rows.append(run(f"b{lanes}", batch_on=True, lanes=lanes))
    best = max(rows, key=lambda r: r["checktx_per_s"])
    return {
        "txs": n_txs,
        "threads": threads,
        "launch_overhead_ms": launch_ms,
        "emulated_launch": True,
        "signed": True,
        "target_device_checktx_per_s": 100_000,
        "legacy": legacy,
        "batched": rows,
        "speedup": round(best["checktx_per_s"] / legacy["checktx_per_s"], 3),
    }


def drive_mesh_scaling(batch: int, reps: int, device_counts=(1, 2, 4, 8)) -> dict | None:
    """`sharded_verify` section: the REAL mesh kernels at mesh widths
    1/2/4/8 — verifies/s, per-launch commit-tally latency, and scaling
    efficiency vs linear from the devices=1 figure. On the CPU CI shape
    the "devices" are XLA virtual host devices (threads over the same
    cores — expect sub-linear; the section exists so a TPU pod reseeds
    it with ICI numbers), flagged `virtual_devices`."""
    import jax
    import numpy as np

    from tendermint_tpu.parallel.mesh import MeshManager
    from tendermint_tpu.services.verifier import ShardedBatchVerifier

    have = len(jax.devices())
    counts = [c for c in device_counts if c <= have]
    if len(counts) < 2:
        return None
    pubs, msgs, sigs = _make_sigs(batch)
    triples = list(zip(pubs, msgs, sigs))
    powers = np.full(batch, 3, dtype=np.int32)
    rows = []
    base_vps = None
    for c in counts:
        sys.stderr.write(f"  mesh width {c}: compiling + timing...\n")
        mgr = MeshManager(devices=list(jax.devices())[:c])
        v = ShardedBatchVerifier(mesh=mgr, min_device_batch=1)
        mask, tally = v.verify_batch_with_powers(triples, powers)  # warm
        assert bool(mask.all()) and tally == 3 * batch, (int(mask.sum()), tally)
        t0 = time.perf_counter()
        for _ in range(reps):
            mask, tally = v.verify_batch_with_powers(triples, powers)
        dt = time.perf_counter() - t0
        vps = batch * reps / dt
        if base_vps is None:
            base_vps = vps
        rows.append(
            {
                "devices": c,
                "verifies_per_s": round(vps, 1),
                "commit_ms": round(dt / reps * 1e3, 3),
                "scaling_efficiency": round(vps / (base_vps * c), 3),
            }
        )
    return {
        "batch": batch,
        "reps": reps,
        "backend": jax.default_backend(),
        "virtual_devices": jax.default_backend() == "cpu",
        "widths": rows,
    }


def _finality_pctls(gaps: list[float]) -> tuple[float | None, float | None]:
    gaps = sorted(gaps)
    if not gaps:
        return None, None
    p50 = gaps[len(gaps) // 2]
    p99 = gaps[min(len(gaps) - 1, int(0.99 * (len(gaps) - 1)))]
    return p50, p99


def drive_finality(
    heights_idle: int, heights_loaded: int, n_vals: int = 4, feeders: int = 2
) -> dict:
    """`finality` section: commit-to-commit p50/p99 on a LIVE
    in-process validator net (full `node.Node` instances: p2p + mempool
    + RPC), idle and under open-loop CheckTx traffic, read back from
    the nodes' HeightLedgers — the exact records `/health`'s SLO window
    and `tools/finality_report.py` consume. The regression floor for
    ROADMAP item 3 (cross-height pipelined consensus): the pipelining
    PR must move these numbers down, and `tools/bench_gate.py` refuses
    a PR that silently moves them up."""
    import tempfile
    import threading

    from tendermint_tpu.consensus.config import ConsensusConfig
    from tendermint_tpu.testing.nemesis import Nemesis

    def fast(cfg):
        # full consensus speed (skip_timeout_commit): measure the
        # machinery's latency, not the production commit pacing.
        # Blocks are capped so the loaded half measures finality under
        # steady traffic instead of degenerating into a max-throughput
        # contest the in-process GIL always loses.
        cfg.consensus = ConsensusConfig.test_config()
        cfg.consensus.max_block_size_txs = 256

    warm = 2
    path_counts: dict[str, int] = {}

    def summarize(recs: list[dict]) -> dict:
        gaps = [
            r["finality_s"]
            for r in recs
            if isinstance(r.get("finality_s"), (int, float))
        ]
        for r in recs:
            label = r.get("critical_path")
            if label:
                path_counts[label] = path_counts.get(label, 0) + 1
        p50, p99 = _finality_pctls(gaps)
        return {
            "heights": len(recs),
            "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
            "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
        }

    def measure_idle_leg(mutator, warm_leg, heights, label):
        """One live-net idle run -> p50/p99 + pipeline overlap stats."""
        with tempfile.TemporaryDirectory(prefix=f"hotpath-fin-{label}-") as h:
            with Nemesis(
                n_vals,
                home=h,
                node_factory=Nemesis.full_node_factory(config_mutator=mutator),
            ) as net:
                lead = net.nodes[0]
                net.wait_height(warm_leg + heights, timeout=300)
                recs = [
                    r
                    for r in lead.node.height_ledger.recent()
                    if warm_leg < r["height"] <= warm_leg + heights
                ]
                gaps = [
                    r["finality_s"]
                    for r in recs
                    if isinstance(r.get("finality_s"), (int, float))
                ]
                p50, p99 = _finality_pctls(gaps)
                pipelined = [r for r in recs if r.get("pipelined")]
                out = {
                    "heights": len(recs),
                    "p50_ms": round(p50 * 1e3, 3) if p50 is not None else None,
                    "p99_ms": round(p99 * 1e3, 3) if p99 is not None else None,
                    "pipelined_heights": len(pipelined),
                }
                if pipelined:
                    out["apply_overlap_ms_mean"] = round(
                        sum(r.get("apply_overlap_s") or 0.0 for r in pipelined)
                        / len(pipelined)
                        * 1e3,
                        3,
                    )
                return out

    def pipeline_ab(heights: int = 6) -> dict:
        """Serial-vs-pipelined on the live net at the PRODUCTION commit
        pacing (timeout_commit=1s, the deployment default): the serial
        leg is the pre-pipeline configuration (strictly serial finalize
        + the fixed timeout ladder), the pipelined leg is this PR
        (overlapped apply + measured-latency timeouts). This is where
        ROADMAP item 3's floors move DOWN — a healthy net stops
        sleeping out the static commit pacing, and the apply rides
        under the next height's voting."""
        from tendermint_tpu.consensus.ticker import AdaptiveTimeouts

        def prod(pipe):
            def mut(cfg):
                c = ConsensusConfig.test_config()
                c.timeout_commit = 1000  # production pacing
                c.skip_timeout_commit = False  # production default
                c.pipeline_commit = pipe
                c.adaptive_timeouts = pipe
                c.max_block_size_txs = 256
                cfg.consensus = c

            return mut

        serial = measure_idle_leg(prod(False), 2, heights, "serial")
        # warm past the derivation gate so measured timeouts engage
        warm_pipe = AdaptiveTimeouts.MIN_HEIGHTS + 1
        pipelined = measure_idle_leg(prod(True), warm_pipe, heights + 2, "pipe")
        speedup = None
        if serial["p50_ms"] and pipelined["p50_ms"]:
            speedup = round(serial["p50_ms"] / pipelined["p50_ms"], 3)
        return {
            "commit_pacing_ms": 1000,
            "serial": serial,
            "pipelined": pipelined,
            "speedup_idle_p50": speedup,
        }

    with tempfile.TemporaryDirectory(prefix="hotpath-finality-") as home:
        with Nemesis(
            n_vals,
            home=home,
            node_factory=Nemesis.full_node_factory(config_mutator=fast),
        ) as net:
            lead = net.nodes[0]
            net.wait_height(warm + heights_idle, timeout=180)
            idle = summarize(
                [
                    r
                    for r in lead.node.height_ledger.recent()
                    if warm < r["height"] <= warm + heights_idle
                ]
            )
            h0 = lead.store.height
            stop = threading.Event()

            def feeder(k: int) -> None:
                # open-loop but depth-bounded: keep a steady backlog in
                # front of the proposer without letting the pool (and
                # the gossip fan-out) grow unboundedly — the bench
                # measures finality under traffic, not pool growth
                i = 0
                while not stop.is_set():
                    if lead.node.mempool.size() < 1024:
                        try:
                            lead.node.mempool.check_tx_async(
                                b"fin%d/k%d=%d" % (k, i, i)
                            )
                        except Exception:
                            return
                        i += 1
                    time.sleep(0.002)

            threads = [
                threading.Thread(target=feeder, args=(k,), daemon=True)
                for k in range(feeders)
            ]
            for t in threads:
                t.start()
            try:
                net.wait_height(h0 + heights_loaded, timeout=240)
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=5)
            loaded_recs = [
                r
                for r in lead.node.height_ledger.recent()
                if h0 < r["height"] <= h0 + heights_loaded
            ]
            loaded = summarize(loaded_recs)
            txs = sum(r.get("txs", 0) for r in loaded_recs)
            span = sum(
                r["finality_s"]
                for r in loaded_recs
                if isinstance(r.get("finality_s"), (int, float))
            )
            loaded["txs_committed"] = txs
            loaded["committed_tx_per_s"] = round(txs / span, 1) if span else None
    sys.stderr.write(
        "driving serial-vs-pipelined A/B at production commit pacing...\n"
    )
    return {
        "validators": n_vals,
        "consensus_config": "test (skip_timeout_commit)",
        "feeders": feeders,
        "idle": idle,
        "loaded": loaded,
        "critical_path_counts": dict(
            sorted(path_counts.items(), key=lambda kv: -kv[1])
        ),
        "pipeline": pipeline_ab(),
    }


def drive_scenario_finality(names) -> dict:
    """`scenario_finality` section: the declarative scenario library
    (PR 16) run end-to-end — WAN topology shaping + fault timelines +
    validator churn on live Nemesis nets — with each scenario's
    committed finality floor graded by the runner itself. Includes the
    adaptive-timeout A/B on the slow-WAN topology: the adaptive leg
    must converge its propose timeout above the injected one-way delay
    and stop skipping rounds once warm, while the fixed-short leg
    (same fabric, adaptive off, 10 ms propose) keeps paying round
    skips every time the far validator proposes — the measured, not
    asserted, case for measured-latency timeouts on real WAN RTTs."""
    import copy
    import tempfile

    from tendermint_tpu.testing.scenario import SCENARIO_LIBRARY, ScenarioRunner

    out: dict = {"scenarios": {}, "all_pass": True}
    for name in names:
        spec = copy.deepcopy(SCENARIO_LIBRARY[name])
        sys.stderr.write(f"  scenario {name}...\n")
        report = ScenarioRunner(
            home=tempfile.mkdtemp(prefix=f"hotpath-scn-{name}-")
        ).run(spec)
        entry = {
            "ok": report["ok"],
            "elapsed_s": report["elapsed_s"],
            "min_height": min(report["heights"], default=0),
            "finality": report["finality"],
            "round_skips_post_warm": report["round_skips_post_warm"],
        }
        for key in ("epochs", "valset_rebuilds"):
            if key in report:
                entry[key] = report[key]
        if report["failures"]:
            entry["failures"] = report["failures"]
        out["scenarios"][name] = entry
        out["all_pass"] = bool(out["all_pass"] and report["ok"])

    legs: dict = {}
    for label in ("adaptive", "fixed_short"):
        spec = copy.deepcopy(SCENARIO_LIBRARY["slow_wan_validator"])
        spec["name"] = f"slow_wan_{label}"
        if label == "fixed_short":
            spec["config"]["adaptive_timeouts"] = False
            spec["config"]["timeout_propose_ms"] = 10  # < one-way delay
            spec["expect"].pop("adaptive_above_max_delay", None)
            spec["expect"].pop("max_round_skips_post_warm", None)
        sys.stderr.write(f"  A/B leg {label}...\n")
        report = ScenarioRunner(
            home=tempfile.mkdtemp(prefix=f"hotpath-ab-{label}-")
        ).run(spec)
        leg = {
            "ok": report["ok"],
            "round_skips_post_warm": report["round_skips_post_warm"],
            "finality_p50_s": report["finality"].get("p50_s"),
        }
        if "propose_timeout_s" in report:
            leg["propose_timeout_s"] = report["propose_timeout_s"]
            leg["max_one_way_delay_s"] = report["max_one_way_delay_s"]
        legs[label] = leg
        out["all_pass"] = bool(out["all_pass"] and report["ok"])
    # The headline A/B number: how much slower finality gets when the
    # propose timeout is pinned below the one-way WAN delay instead of
    # adapting to it. Round-skip counters only see skip-ahead jumps, so
    # the latency ratio is the robust degradation signal.
    adaptive_p50 = legs["adaptive"].get("finality_p50_s")
    fixed_p50 = legs["fixed_short"].get("finality_p50_s")
    if adaptive_p50 and fixed_p50:
        legs["finality_p50_ratio"] = round(fixed_p50 / adaptive_p50, 3)
    out["adaptive_ab"] = legs
    return out


def drive_gossip_efficiency(n_msgs: int) -> dict:
    """`gossip_efficiency` section (the gossip observatory, PR 17) —
    two halves:

    * **accounting overhead guard**: vote-tagged frames pumped through
      a connected switch pair with `TENDERMINT_TPU_GOSSIPLOG=0` vs on;
      classifying + rolling up every frame (channel name, kind tag,
      per-peer table row, two counter incs) must stay within 3% of
      off. Best-of-3 per half — pipe throughput is scheduler-noisy.
    * **redundancy factor on the 4-node loadgen net**: a short live
      Nemesis run on the flash-crowd WAN fabric under steady load; the
      per-kind delivered/useful factors from the fleet rollup are the
      measured over-gossip numbers (vote > 1.0 = the HasVote race is
      real, the before-number for the ROADMAP item 3 aggregation lane).
    """
    import copy
    import threading as _threading

    from tendermint_tpu.p2p.connection import ChannelDescriptor
    from tendermint_tpu.p2p.peer import NodeInfo
    from tendermint_tpu.p2p.switch import Reactor, Switch, connect_switches
    from tendermint_tpu.testing.scenario import ScenarioRunner

    vote_chan = 0x22
    payload = b"\x06" + b"v" * 160  # vote-tagged, vote-sized

    class _Sink(Reactor):
        def __init__(self) -> None:
            super().__init__()
            self.count = 0
            self.target = 0
            self.done = _threading.Event()

        def get_channels(self):
            return [
                ChannelDescriptor(
                    vote_chan, priority=5, send_queue_capacity=1024
                )
            ]

        def receive(self, chan_id, peer, data) -> None:
            self.count += 1
            if self.count >= self.target:
                self.done.set()

    def run_half() -> tuple[float, int]:
        a = Switch(NodeInfo("a" * 40, "bench-a", "bench-gossip"))
        b = Switch(NodeInfo("b" * 40, "bench-b", "bench-gossip"))
        a.ping_interval = b.ping_interval = 0
        a.add_reactor("sink", _Sink())
        sink = b.add_reactor("sink", _Sink())
        sink.target = n_msgs
        a.start()
        b.start()
        pa, _pb = connect_switches(a, b)
        try:
            t0 = time.perf_counter()
            for _ in range(n_msgs):
                assert pa.send(vote_chan, payload, ctx=None)
            assert sink.done.wait(timeout=60)
            mps = n_msgs / (time.perf_counter() - t0)
            snap = b.gossip.snapshot()
            counted = (
                snap["kinds"].get("vote", {}).get("recv_msgs", 0)
                if snap["enabled"]
                else 0
            )
            return mps, counted
        finally:
            a.stop()
            b.stop()

    prev = os.environ.get("TENDERMINT_TPU_GOSSIPLOG")
    try:
        os.environ["TENDERMINT_TPU_GOSSIPLOG"] = "0"
        run_half()  # warmup: thread spin-up excluded from both halves
        off_mps = max(run_half()[0] for _ in range(3))
        os.environ["TENDERMINT_TPU_GOSSIPLOG"] = "1"
        on_runs = [run_half() for _ in range(3)]
        on_mps = max(r[0] for r in on_runs)
        msgs_counted = max(r[1] for r in on_runs)
    finally:
        if prev is None:
            os.environ.pop("TENDERMINT_TPU_GOSSIPLOG", None)
        else:
            os.environ["TENDERMINT_TPU_GOSSIPLOG"] = prev
    overhead_pct = 100.0 * (1.0 - on_mps / off_mps)

    # redundancy half: 4 full nodes on the flash-crowd WAN fabric under
    # steady load — a real consensus run, so vote/part/tx dedup sites
    # see genuine gossip races
    spec = {
        "name": "gossip_probe",
        "description": "bench probe: 4-node WAN loadgen redundancy",
        "nodes": 4,
        "kind": "full",
        "topology": {
            "placement": ["us-east", "us-west", "eu-west", "us-east"],
            "scale": 0.1,
        },
        "config": {
            "timeout_propose_ms": 1000,
            "timeout_prevote_ms": 300,
            "timeout_precommit_ms": 300,
        },
        "load": {"rate": 25.0, "payload": 64},
        "run": {"target_height": 8, "timeout_s": 120.0},
        "expect": {
            "min_height": 8,
            "gossip": {"require_counted": True},
        },
    }
    sys.stderr.write("  gossip redundancy probe (4-node WAN loadgen)...\n")
    report = ScenarioRunner(
        home=tempfile.mkdtemp(prefix="hotpath-gossip-")
    ).run(copy.deepcopy(spec))
    g = report.get("gossip") or {}
    factors = dict(g.get("redundancy_factor") or {})
    # vote traffic with zero recorded duplicates is a 1.0x factor, not
    # a missing measurement (the floor guards presence + sanity)
    if "vote" not in factors and (g.get("channel_bytes") or {}).get("cns_vote"):
        factors["vote"] = 1.0
    return {
        "messages": n_msgs,
        "accounting_off_msgs_per_s": round(off_mps, 1),
        "accounting_on_msgs_per_s": round(on_mps, 1),
        "overhead_pct": round(overhead_pct, 2),
        "within_3pct": overhead_pct <= 3.0,
        # proof the on half classified + rolled up real frames, not a
        # silently-disabled no-op
        "msgs_counted": msgs_counted,
        "probe_ok": report["ok"],
        "probe_total_bytes": g.get("total_bytes"),
        "probe_channel_bytes": g.get("channel_bytes"),
        "redundancy_factor": factors,
        "redundancy_factor_vote": factors.get("vote"),
        "top_redundant_kind": g.get("top_redundant_kind"),
    }


def drive_wal(n_records: int) -> None:
    from tendermint_tpu.consensus.wal import WAL, EndHeightMessage

    with tempfile.TemporaryDirectory(prefix="hotpath-wal-") as d:
        wal = WAL(os.path.join(d, "cs.wal"))
        for i in range(n_records):
            wal.save(EndHeightMessage(i))
        wal.close()


def _histo(name: str, **labels):
    """(count, sum, p50, p99) of an exported histogram series."""
    from tendermint_tpu.telemetry import REGISTRY

    fam = REGISTRY.get(name)
    if fam is None:
        return 0, 0.0, None, None
    child = fam.labels(**labels) if fam.labelnames else fam._child0()
    snap = child.value
    if snap["count"] == 0:
        return 0, 0.0, None, None
    return (
        snap["count"],
        snap["sum"],
        child.quantile(0.5),
        child.quantile(0.99),
    )


def _histo_snap(name: str, **labels):
    """Raw bucket snapshot of a histogram series (None if the family is
    unregistered) — the baseline half of `_histo_delta`."""
    from tendermint_tpu.telemetry import REGISTRY

    fam = REGISTRY.get(name)
    if fam is None:
        return None
    child = fam.labels(**labels) if fam.labelnames else fam._child0()
    return child.value


def _histo_delta(base, snap):
    """(count, sum, p50, p99) of the observations BETWEEN two snapshots
    — how the bench excludes warmup/compile calls from its percentiles:
    the first (cold) call otherwise lands in the pool and a p99 of two
    seconds gets reported for a sub-millisecond path. Quantiles use the
    registry's interpolation over the diffed cumulative buckets."""
    import math

    if snap is None:
        return 0, 0.0, None, None
    if base is None:
        buckets = snap["buckets"]
        count = snap["count"]
        total = snap["sum"]
    else:
        buckets = [
            (ub, c1 - c0)
            for (ub, c1), (_ub, c0) in zip(snap["buckets"], base["buckets"])
        ]
        count = snap["count"] - base["count"]
        total = snap["sum"] - base["sum"]
    if count <= 0:
        return 0, 0.0, None, None

    def q(qv: float) -> float:
        rank = qv * count
        prev_ub, prev_cum = 0.0, 0
        for ub, cum in buckets:
            if cum >= rank:
                if ub == math.inf:
                    return prev_ub
                width = ub - prev_ub
                in_bucket = cum - prev_cum
                if in_bucket == 0:
                    return ub
                return prev_ub + width * (rank - prev_cum) / in_bucket
            prev_ub, prev_cum = ub, cum
        return prev_ub

    return count, total, q(0.5), q(0.99)


_VERIFY_BACKENDS = ("host", "device", "tables", "mesh")
_HASH_BACKENDS = ("host", "device", "mesh")


def snapshot_baselines() -> dict:
    """Per-backend verify/hash histogram snapshots taken AFTER the
    warmup pass — the summaries report only what happened since."""
    base: dict = {}
    for b in _VERIFY_BACKENDS:
        base[("verify_seconds", b)] = _histo_snap(
            "tendermint_verify_seconds", backend=b
        )
        base[("verify_batch_size", b)] = _histo_snap(
            "tendermint_verify_batch_size", backend=b
        )
    for b in _HASH_BACKENDS:
        base[("hash_seconds", b)] = _histo_snap(
            "tendermint_hash_seconds", backend=b
        )
        base[("hash_batch_leaves", b)] = _histo_snap(
            "tendermint_hash_batch_leaves", backend=b
        )
    return base


def backend_summary(backend: str, base: dict | None = None) -> dict | None:
    b = base or {}
    n_calls, t_total, p50, p99 = _histo_delta(
        b.get(("verify_seconds", backend)),
        _histo_snap("tendermint_verify_seconds", backend=backend),
    )
    _n, sig_total, _, _ = _histo_delta(
        b.get(("verify_batch_size", backend)),
        _histo_snap("tendermint_verify_batch_size", backend=backend),
    )
    if n_calls == 0 or t_total <= 0:
        return None
    return {
        "calls": n_calls,
        "signatures": sig_total,
        "verifies_per_s": round(sig_total / t_total, 1),
        "p50_ms": round(p50 * 1e3, 3),
        "p99_ms": round(p99 * 1e3, 3),
    }


def hash_summary(backend: str, base: dict | None = None) -> dict | None:
    b = base or {}
    n_calls, t_total, p50, p99 = _histo_delta(
        b.get(("hash_seconds", backend)),
        _histo_snap("tendermint_hash_seconds", backend=backend),
    )
    _n, leaves, _, _ = _histo_delta(
        b.get(("hash_batch_leaves", backend)),
        _histo_snap("tendermint_hash_batch_leaves", backend=backend),
    )
    if n_calls == 0 or t_total <= 0:
        return None
    return {
        "calls": n_calls,
        "leaves": leaves,
        "leaves_per_s": round(leaves / t_total, 1),
        "p50_ms": round(p50 * 1e3, 3),
        "p99_ms": round(p99 * 1e3, 3),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="BENCH_hotpath.json")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument(
        "--sizes", default="64,256,1024", help="comma-separated batch sizes"
    )
    ap.add_argument(
        "--wal-records", type=int, default=256, dest="wal_records"
    )
    ap.add_argument(
        "--statesync-kb",
        type=int,
        default=256,
        dest="statesync_kb",
        help="snapshot payload size driven through take+verify (0 skips)",
    )
    ap.add_argument(
        "--fastsync-blocks",
        type=int,
        default=96,
        dest="fastsync_blocks",
        help="chain length replayed through the fast-sync pipeline (0 skips)",
    )
    ap.add_argument(
        "--fastsync-vals",
        type=int,
        default=8,
        dest="fastsync_vals",
        help="validators signing each bench commit",
    )
    ap.add_argument(
        "--dedup-heights",
        type=int,
        default=4,
        dest="dedup_heights",
        help="heights replayed through the gossip-then-commit dedup bench (0 skips)",
    )
    ap.add_argument(
        "--dedup-vals",
        type=int,
        default=64,
        dest="dedup_vals",
        help="validators signing each dedup-bench height",
    )
    ap.add_argument(
        "--coalesce-rounds",
        type=int,
        default=6,
        dest="coalesce_rounds",
        help="rounds each of the four consumers drives through the coalescer (0 skips)",
    )
    ap.add_argument(
        "--coalesce-batch",
        type=int,
        default=32,
        dest="coalesce_batch",
        help="signatures per consumer request in the coalesce bench",
    )
    ap.add_argument(
        "--launch-ms",
        type=float,
        default=86.0,
        dest="launch_ms",
        help="emulated device launch cost on CPU (PLATFORM_NOTES axon "
        "tunnel figure); ignored on a real device backend",
    )
    ap.add_argument(
        "--no-device",
        action="store_true",
        help="skip device backends even on TPU",
    )
    ap.add_argument(
        "--mesh",
        action="store_true",
        help="run the sharded_verify mesh-scaling section (devices="
        "1/2/4/8; pays one kernel compile per mesh width — minutes on "
        "XLA:CPU, cached-fast on TPU)",
    )
    ap.add_argument(
        "--mesh-batch",
        type=int,
        default=256,
        dest="mesh_batch",
        help="signatures per launch in the mesh-scaling section",
    )
    ap.add_argument(
        "--ingress",
        action="store_true",
        help="run the mempool_ingress section (batched+sharded CheckTx "
        "admission vs the legacy one-at-a-time path at 1/4/8 lanes)",
    )
    ap.add_argument(
        "--ingress-txs",
        type=int,
        default=1024,
        dest="ingress_txs",
        help="signed txs per ingress run",
    )
    ap.add_argument(
        "--ingress-threads",
        type=int,
        default=8,
        dest="ingress_threads",
        help="concurrent CheckTx submitter threads",
    )
    ap.add_argument(
        "--ingress-launch-ms",
        type=float,
        default=5.0,
        dest="ingress_launch_ms",
        help="emulated device launch cost per ingress verify call "
        "(kept small so the legacy run finishes; real figure is the "
        "86 ms axon tunnel)",
    )
    ap.add_argument(
        "--reads",
        action="store_true",
        help="run the reads section (light-client walks: sequential "
        "InquiringCertifier vs batched bisection over a 256-height "
        "rotating chain, + proofs-served/s)",
    )
    ap.add_argument(
        "--reads-heights",
        type=int,
        default=256,
        dest="reads_heights",
        help="chain length the read-path walks bridge",
    )
    ap.add_argument(
        "--reads-vals",
        type=int,
        default=8,
        dest="reads_vals",
        help="validators signing each reads-bench height",
    )
    ap.add_argument(
        "--reads-rotate-every",
        type=int,
        default=8,
        dest="reads_rotate_every",
        help="heights between single-validator rotations in the reads "
        "chain (controls how far each trust jump can skip)",
    )
    ap.add_argument(
        "--reads-launch-ms",
        type=float,
        default=86.0,
        dest="reads_launch_ms",
        help="emulated launch cost per read-path verify call (the "
        "86 ms axon-tunnel figure, like --launch-ms: the walk A/B is "
        "launch-count bound, so the real launch cost is the honest "
        "weighting)",
    )
    ap.add_argument(
        "--finality-heights",
        type=int,
        default=12,
        dest="finality_heights",
        help="idle heights measured in the finality section (0 skips it)",
    )
    ap.add_argument(
        "--finality-loaded",
        type=int,
        default=10,
        dest="finality_loaded",
        help="heights measured under open-loop CheckTx traffic",
    )
    ap.add_argument(
        "--scenarios",
        default="churn_small,flash_crowd",
        help="comma-separated scenario library entries for the "
        "scenario_finality section (empty skips the section; the "
        "adaptive-timeout A/B on the slow-WAN topology always rides "
        "with it)",
    )
    ap.add_argument(
        "--gossip-msgs",
        type=int,
        default=4000,
        help="frames for the gossip-accounting overhead guard "
        "(0 skips the gossip_efficiency section)",
    )
    args = ap.parse_args(argv)
    sizes = [int(s) for s in args.sizes.split(",") if s]

    import jax

    on_device = jax.default_backend() != "cpu" and not args.no_device
    t0 = time.time()
    # Warmup pass over the SAME shapes, EXCLUDED from the percentile
    # pool (the tracing_overhead section's discipline applied to every
    # backend summary): the first call per shape pays imports/compiles/
    # memo fills — seconds against a sub-ms steady state — and would
    # own the reported p99 forever.
    sys.stderr.write("warmup pass (cold-start excluded from percentiles)...\n")
    drive_verify_host(sizes, 1)
    drive_hash(sizes, 1, "host")
    if on_device:
        drive_verify_device(sizes, 1)
        drive_verify_tables(n_vals=max(sizes), stack=1, reps=1)
        drive_hash(sizes, 1, "device")
    baselines = snapshot_baselines()
    sys.stderr.write(f"driving host verify {sizes} x{args.reps}...\n")
    drive_verify_host(sizes, args.reps)
    sys.stderr.write(f"driving host merkle {sizes} x{args.reps}...\n")
    drive_hash(sizes, args.reps, "host")
    sys.stderr.write(f"driving WAL fsync x{args.wal_records}...\n")
    drive_wal(args.wal_records)
    if args.statesync_kb > 0:
        sys.stderr.write(
            f"driving statesync snapshot+verify {args.statesync_kb}KB x{args.reps}...\n"
        )
        drive_statesync(args.statesync_kb, chunk_size=16 * 1024, reps=args.reps)
    if on_device:
        sys.stderr.write("driving device verify/tables/merkle...\n")
        drive_verify_device(sizes, args.reps)
        drive_verify_tables(n_vals=max(sizes), stack=8, reps=args.reps)
        drive_hash(sizes, args.reps, "device")
    # snapshot the backend summaries BEFORE the fast-sync replay: its
    # chain build + window verifies would otherwise pollute the
    # per-backend verifies/s with small consensus-shaped batches
    verify_summaries = {
        b: s
        for b in _VERIFY_BACKENDS
        if (s := backend_summary(b, baselines)) is not None
    }
    hash_summaries = {
        b: s
        for b in _HASH_BACKENDS
        if (s := hash_summary(b, baselines)) is not None
    }
    fastsync_pipeline = None
    if args.fastsync_blocks > 0:
        sys.stderr.write(
            f"driving fast-sync pipeline {args.fastsync_blocks} blocks x "
            f"{args.fastsync_vals} vals (sync vs overlapped)...\n"
        )
        fastsync_pipeline = drive_fastsync_pipeline(
            args.fastsync_blocks, args.fastsync_vals, args.launch_ms, on_device
        )
    dedup_steady_state = None
    if args.dedup_heights > 0:
        sys.stderr.write(
            f"driving dedup steady-state {args.dedup_heights} heights x "
            f"{args.dedup_vals} vals (cache off vs on)...\n"
        )
        dedup_steady_state = drive_dedup_steady_state(
            args.dedup_heights, args.dedup_vals, args.launch_ms
        )
    coalesce_multiconsumer = None
    if args.coalesce_rounds > 0:
        sys.stderr.write(
            f"driving 4-consumer coalescer {args.coalesce_rounds} rounds x "
            f"{args.coalesce_batch} sigs...\n"
        )
        coalesce_multiconsumer = drive_coalesce_multiconsumer(
            args.coalesce_rounds, args.coalesce_batch, args.launch_ms
        )
    tracing_overhead = None
    if args.dedup_heights > 0:
        sys.stderr.write(
            f"driving tracing overhead guard {args.dedup_heights} heights x "
            f"{args.dedup_vals} vals (sampling off vs 1/64)...\n"
        )
        tracing_overhead = drive_tracing_overhead(
            args.dedup_heights, args.dedup_vals, args.launch_ms
        )
    profiler_overhead = None
    if args.dedup_heights > 0:
        sys.stderr.write(
            f"driving profiler overhead guard {args.dedup_heights} heights x "
            f"{args.dedup_vals} vals (off vs 29 Hz + lock timing)...\n"
        )
        profiler_overhead = drive_profiler_overhead(
            args.dedup_heights, args.dedup_vals, args.launch_ms
        )
    device_efficiency = None
    if args.dedup_heights > 0:
        sys.stderr.write(
            f"driving device-efficiency guard {args.dedup_heights} heights x "
            f"{args.dedup_vals} vals (ledger off vs on + mesh occupancy)...\n"
        )
        device_efficiency = drive_device_efficiency(
            args.dedup_heights, args.dedup_vals, args.launch_ms
        )
    mempool_ingress = None
    if args.ingress:
        sys.stderr.write(
            f"driving mempool ingress {args.ingress_txs} signed txs x "
            f"{args.ingress_threads} threads (legacy vs batched @ 1/4/8 lanes)...\n"
        )
        mempool_ingress = drive_mempool_ingress(
            args.ingress_txs, args.ingress_threads, args.ingress_launch_ms
        )
    reads = None
    if args.reads:
        sys.stderr.write(
            f"driving read-path walks: {args.reads_heights} heights x "
            f"{args.reads_vals} vals, rotate every "
            f"{args.reads_rotate_every} (sequential vs bisect)...\n"
        )
        reads = drive_reads(
            args.reads_heights,
            args.reads_vals,
            args.reads_rotate_every,
            args.reads_launch_ms,
        )
    sharded_verify = None
    if args.mesh:
        sys.stderr.write(
            f"driving mesh scaling, batch {args.mesh_batch} at widths 1/2/4/8...\n"
        )
        sharded_verify = drive_mesh_scaling(args.mesh_batch, args.reps)

    # WAL stats are captured BEFORE the finality net runs: its four
    # live nodes fsync their own consensus WALs into the same histogram
    wal_count, wal_sum, wal_p50, wal_p99 = _histo("tendermint_wal_fsync_seconds")
    finality = None
    if args.finality_heights > 0:
        sys.stderr.write(
            f"driving live-net finality: {args.finality_heights} idle + "
            f"{args.finality_loaded} loaded heights x 4 validators...\n"
        )
        finality = drive_finality(args.finality_heights, args.finality_loaded)
    scenario_finality = None
    scenario_names = [s for s in args.scenarios.split(",") if s]
    if scenario_names:
        sys.stderr.write(
            f"driving scenario library: {', '.join(scenario_names)} "
            "+ adaptive-timeout A/B...\n"
        )
        scenario_finality = drive_scenario_finality(scenario_names)
    gossip_efficiency = None
    if args.gossip_msgs > 0:
        sys.stderr.write(
            f"driving gossip-accounting guard: {args.gossip_msgs} frames "
            "(off vs on) + 4-node WAN redundancy probe...\n"
        )
        gossip_efficiency = drive_gossip_efficiency(args.gossip_msgs)
    detail = {
        "wall_s": round(time.time() - t0, 2),
        "backend": jax.default_backend(),
        "verify": verify_summaries,
        "hash": hash_summaries,
        "statesync": statesync_summary(),
        "fastsync_pipeline": fastsync_pipeline,
        "dedup_steady_state": dedup_steady_state,
        "coalesce_multiconsumer": coalesce_multiconsumer,
        "tracing_overhead": tracing_overhead,
        "profiler_overhead": profiler_overhead,
        "device_efficiency": device_efficiency,
        "mempool_ingress": mempool_ingress,
        "reads": reads,
        "sharded_verify": sharded_verify,
        "finality": finality,
        "scenario_finality": scenario_finality,
        "gossip_efficiency": gossip_efficiency,
        "wal_fsync": {
            "count": wal_count,
            "fsyncs_per_s": round(wal_count / wal_sum, 1) if wal_sum else None,
            "p50_ms": round(wal_p50 * 1e3, 3) if wal_p50 is not None else None,
            "p99_ms": round(wal_p99 * 1e3, 3) if wal_p99 is not None else None,
        },
    }
    # headline: the fastest verify backend exercised this run
    best_backend, best = max(
        detail["verify"].items(), key=lambda kv: kv[1]["verifies_per_s"]
    )
    out = {
        "metric": f"hotpath_{best_backend}_verifies_per_s",
        "value": best["verifies_per_s"],
        "unit": "verifies/s",
        "detail": detail,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
