"""Contention report: the whole-node on-CPU/blocked waterfall.

Merges the three legs of the contention observatory — profiler stack
samples (`telemetry/profiler.py`), ranked-lock wait/hold stats
(`utils/lockrank.py`), and the unified queue-wait table
(`telemetry/views.py`) — into one per-subsystem waterfall that answers
the question ROADMAP item 4 starts from: **which thread(s) must leave
the process first?**

    # against a live node (profiling armed via TENDERMINT_TPU_PROFILE_HZ)
    python tools/contention_report.py --rpc 127.0.0.1:26657

    # from a saved dump_telemetry?profile=1 JSON
    python tools/contention_report.py --dump dump.json

    # flamegraph input (collapsed-stack lines) on the side
    python tools/contention_report.py --rpc ... --collapsed out.collapsed

Output: a text waterfall (on-CPU vs blocked share per subsystem, with
the blocked-by reason split and the queue waits joined in), the
most-contended lock with its hottest acquire site, the dominant
blocked subsystem, and the verdict line naming the top on-CPU
subsystem as the first multi-process extraction candidate. `--json`
writes the structured report.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request

# the subsystems a "leave the process" verdict can name — ambient
# buckets (main/other) aren't extraction candidates
_VERDICT_EXCLUDE = {"main", "other"}


def fetch_profile_rpc(addr: str, timeout: float = 30.0) -> dict:
    """dump_telemetry(profile=1) over JSON-RPC; returns the full dump
    (the `profile` key holds the observatory view)."""
    req = urllib.request.Request(
        f"http://{addr}/",
        data=json.dumps(
            {
                "jsonrpc": "2.0",
                "id": 1,
                "method": "dump_telemetry",
                "params": {"spans": 0, "profile": 1},
            }
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        out = json.load(resp)
    if "error" in out:
        raise RuntimeError(out["error"])
    return out["result"]


def _profile_of(dump: dict) -> dict:
    """Accept a full dump_telemetry payload OR a bare profile view."""
    if "profile" in dump:
        return dump["profile"]
    if "profiler" in dump:
        return dump
    raise ValueError(
        "no profile view found — dump with profile=1 (and arm the "
        "profiler: TENDERMINT_TPU_PROFILE_HZ or a boost window)"
    )


def build_report(profile: dict) -> dict:
    """The structured report: per-subsystem waterfall rows + the three
    named answers (most-contended lock, dominant blocked subsystem,
    top on-CPU subsystem = the extraction verdict)."""
    prof = profile.get("profiler") or {}
    locks = (profile.get("locks") or {}).get("locks") or []
    queues = profile.get("queues") or {}
    subsystems = prof.get("subsystems") or {}

    rows = []
    total = sum(r["on_cpu"] + r["blocked"] for r in subsystems.values()) or 1
    for sub, r in subsystems.items():
        samples = r["on_cpu"] + r["blocked"]
        blocked_by = dict(
            sorted(
                (r.get("blocked_by") or {}).items(),
                key=lambda kv: kv[1],
                reverse=True,
            )
        )
        qsub = {
            "consensus": queues.get("consensus"),
            "ingress": queues.get("ingress"),
            "coalescer": queues.get("coalescer"),
            "dispatch": queues.get("dispatch"),
            "p2p_send": queues.get("p2p_send"),
        }.get(sub)
        rows.append(
            {
                "subsystem": sub,
                "samples": samples,
                "share_pct": round(100.0 * samples / total, 1),
                "on_cpu": r["on_cpu"],
                "blocked": r["blocked"],
                "on_cpu_pct": round(100.0 * r["on_cpu"] / samples, 1)
                if samples
                else 0.0,
                "blocked_by": blocked_by,
                "queue_waits": qsub or {},
            }
        )
    rows.sort(key=lambda r: r["samples"], reverse=True)

    most_contended = locks[0] if locks else None
    blocked_rows = [r for r in rows if r["blocked"] > 0]
    dominant_blocked = (
        max(blocked_rows, key=lambda r: r["blocked"]) if blocked_rows else None
    )
    cpu_rows = [
        r
        for r in rows
        if r["on_cpu"] > 0 and r["subsystem"] not in _VERDICT_EXCLUDE
    ]
    top_cpu = max(cpu_rows, key=lambda r: r["on_cpu"]) if cpu_rows else None
    total_cpu = sum(r["on_cpu"] for r in rows) or 1

    verdict = None
    if top_cpu is not None:
        verdict = {
            "move_out_first": top_cpu["subsystem"],
            "on_cpu_share_pct": round(
                100.0 * top_cpu["on_cpu"] / total_cpu, 1
            ),
            "reason": (
                f"{top_cpu['subsystem']} burns the largest on-CPU share "
                f"({round(100.0 * top_cpu['on_cpu'] / total_cpu, 1)}% of all "
                "on-CPU samples) under the shared GIL — first candidate "
                "to leave the process (ROADMAP item 4, multi-process "
                "node architecture)"
            ),
        }

    return {
        "samples": prof.get("samples", 0),
        "ticks": prof.get("ticks", 0),
        "hz": prof.get("hz"),
        "cpu_clock": prof.get("cpu_clock"),
        "waterfall": rows,
        "most_contended_lock": most_contended,
        "dominant_blocked_subsystem": (
            {
                "subsystem": dominant_blocked["subsystem"],
                "blocked": dominant_blocked["blocked"],
                "blocked_by": dominant_blocked["blocked_by"],
            }
            if dominant_blocked is not None
            else None
        ),
        "verdict": verdict,
        "threads": prof.get("threads") or {},
        "top_stacks": prof.get("top_stacks") or [],
    }


def _bar(pct: float, width: int = 20) -> str:
    filled = int(round(pct / 100.0 * width))
    return "#" * filled + "." * (width - filled)


def _fmt_blocked_by(blocked_by: dict, blocked: int) -> str:
    if not blocked_by or not blocked:
        return ""
    parts = [
        f"{reason or 'other'} {round(100.0 * n / blocked)}%"
        for reason, n in list(blocked_by.items())[:3]
    ]
    return ", ".join(parts)


def render_text(report: dict) -> str:
    """The operator-facing waterfall."""
    out = [
        "contention observatory — per-subsystem on-CPU vs blocked "
        f"({report['samples']} samples @ {report['hz']} Hz"
        + ("" if report.get("cpu_clock") else "; NO per-thread CPU clocks")
        + ")",
        "",
        f"{'subsystem':<12} {'samples':>7} {'share':>6} {'on-CPU':>7} "
        f"{'blocked':>7}  {'on-CPU%':>7} {'':20}  blocked-by",
    ]
    for r in report["waterfall"]:
        out.append(
            f"{r['subsystem']:<12} {r['samples']:>7} {r['share_pct']:>5.1f}% "
            f"{r['on_cpu']:>7} {r['blocked']:>7}  {r['on_cpu_pct']:>6.1f}% "
            f"{_bar(r['on_cpu_pct'])}  "
            f"{_fmt_blocked_by(r['blocked_by'], r['blocked'])}"
        )
        waits = r.get("queue_waits")
        if waits:
            for key, w in list(waits.items())[:4]:
                if not isinstance(w, dict) or "count" not in w:
                    continue
                label = f"queue[{key}]" if key else "queue"
                out.append(
                    f"{'':12} {label}: {w['count']} waits, "
                    f"p50 {w['p50_ms']} ms, p99 {w['p99_ms']} ms, "
                    f"total {w['total_s']} s"
                )
    out.append("")
    lock = report.get("most_contended_lock")
    if lock:
        site = (lock.get("top_sites") or [{}])[0]
        out.append(
            f"most-contended lock: {lock['lock']} — "
            f"{round(lock['wait_s'], 3)} s waited over {lock['wait_count']} "
            f"acquires (max {round(lock['wait_max_s'] * 1e3, 2)} ms), "
            f"{round(lock['hold_s'], 3)} s held"
            + (
                f"; hottest site {site.get('site')} ({site.get('count')} waits)"
                if site
                else ""
            )
        )
    else:
        out.append("most-contended lock: none recorded (lock timing disarmed?)")
    dom = report.get("dominant_blocked_subsystem")
    if dom:
        out.append(
            f"dominant blocked subsystem: {dom['subsystem']} "
            f"({dom['blocked']} blocked samples; "
            f"{_fmt_blocked_by(dom['blocked_by'], dom['blocked'])})"
        )
    verdict = report.get("verdict")
    if verdict:
        out.append(f"verdict: {verdict['reason']}")
    return "\n".join(out)


def collapsed_lines(profile_or_report: dict) -> list[str]:
    """Flamegraph collapsed-stack lines (`stack count`), from whichever
    shape the caller has (profile view, report, or profiler snapshot)."""
    prof = profile_or_report.get("profiler") or profile_or_report
    stacks = prof.get("top_stacks") or []
    items = sorted(
        ((s["stack"], s["count"]) for s in stacks),
        key=lambda kv: (-kv[1], kv[0]),
    )
    return [f"{stack} {count}" for stack, count in items]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--rpc", help="host:port of a live node's RPC listener")
    src.add_argument("--dump", help="saved dump_telemetry?profile=1 JSON file")
    ap.add_argument("--json", dest="json_out", default="", help="write the structured report here")
    ap.add_argument(
        "--collapsed",
        default="",
        help="write flamegraph collapsed-stack lines here",
    )
    args = ap.parse_args(argv)

    if args.rpc:
        dump = fetch_profile_rpc(args.rpc)
    else:
        with open(args.dump, "r", encoding="utf-8") as f:
            dump = json.load(f)
    profile = _profile_of(dump)
    report = build_report(profile)
    print(render_text(report))
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
        print(f"\nreport -> {args.json_out}")
    if args.collapsed:
        lines = collapsed_lines(profile)
        with open(args.collapsed, "w", encoding="utf-8") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
        print(f"collapsed stacks -> {args.collapsed} ({len(lines)} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
