"""Gossip report: the cross-node bandwidth waterfall + propagation map.

Merges N nodes' gossip-observatory dumps (`dump_telemetry?gossip=1` —
per-peer x per-channel x per-kind traffic tables, per-kind redundancy
counters, first-seen propagation stamps from `telemetry/gossiplog.py`)
into the per-channel bandwidth waterfall, the duplicate-delivery
redundancy ranking, and the region-to-region propagation latency matrix
(first-seen wall-clock deltas joined to `testing/topology.py`-style
placement labels), and **names the top waste source**. The network twin
of `tools/device_report.py`.

This is the measurement ROADMAP items 3/5/6 are judged against: vote
gossip that scales per-validator is exactly what item 3's aggregation
lane must collapse, the per-channel byte split is item 5's 1k-validator
scale budget, and the mempool/receipt fan-out numbers are item 6's cost
model.

    # against live nodes (one --rpc per node, placement optional)
    python tools/gossip_report.py --rpc 127.0.0.1:26657 --rpc 127.0.0.1:26660 \\
        --placement us-east,eu-west

    # from saved dump_telemetry JSON dumps
    python tools/gossip_report.py --dumps node*/gossip.json

Output: the per-channel waterfall (bytes + message split, % of fleet
total), the per-kind redundancy ranking (duplicate deliveries, wasted
bytes, delivered/useful factor), the propagation matrix, and the
fix-first verdict. `--json` writes the structured report.
"""

from __future__ import annotations

import argparse
import glob as glob_mod
import json
import sys
import urllib.request

import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# waste sources the verdict can name, with the ROADMAP pointer each one
# implies at committee scale
_FIXES = {
    "vote_redundancy": (
        "votes are the top duplicated kind — per-validator vote gossip "
        "is the traffic class ROADMAP item 3's BLS/aggregation lane "
        "exists to collapse (one aggregate per round instead of N "
        "signatures x N peers); until then, tighten HasVote-driven "
        "suppression in the consensus reactor's gossip threads"
    ),
    "block_part_redundancy": (
        "block parts are re-shipped to holders — target part gossip by "
        "the peer's PartSet bitmap before pushing; this is the "
        "bandwidth line that dominates ROADMAP item 5's 1k-validator "
        "scenario budget"
    ),
    "tx_redundancy": (
        "peers cross-ship txs the dup-cache already holds — announce "
        "tx hashes before bodies (or track per-peer send sets); the "
        "same fan-out discipline ROADMAP item 6's receipt layer needs "
        "at millions-of-clients scale"
    ),
    "evidence_redundancy": (
        "the evidence rebroadcast routine re-offers pending batches "
        "flat-rate — back off per peer once acked; cheap, but it rides "
        "the same channel budget as item 5's scale target"
    ),
    "vote_bandwidth": (
        "no pathological duplication, but the vote channel still "
        "dominates fleet bytes — that is the per-validator scaling "
        "wall ROADMAP item 3's aggregation lane removes and item 5's "
        "1k-validator scenario will hit first"
    ),
    "data_bandwidth": (
        "block-part traffic dominates fleet bytes — raise part size / "
        "compress parts or gossip by bitmap; the item 5 scale budget "
        "is mostly this channel"
    ),
    "mempool_bandwidth": (
        "tx gossip dominates fleet bytes — batch tx frames and dedupe "
        "by announce; the fan-out cost model for ROADMAP item 6"
    ),
}

_CHANNEL_FIX = {
    "cns_vote": "vote_bandwidth",
    "cns_data": "data_bandwidth",
    "mempool": "mempool_bandwidth",
}

_KIND_FIX = {
    "vote": "vote_redundancy",
    "block_part": "block_part_redundancy",
    "tx": "tx_redundancy",
    "evidence": "evidence_redundancy",
}

# redundant-kind -> wire-kind join (evidence dedups per item, the wire
# ships lists)
_WIRE_KIND = {"evidence": "evidence_list"}


def fetch_gossip_rpc(addr: str, timeout: float = 30.0) -> dict:
    """dump_telemetry(gossip=1) over JSON-RPC; returns the gossip view."""
    req = urllib.request.Request(
        f"http://{addr}/",
        data=json.dumps(
            {
                "jsonrpc": "2.0",
                "id": 1,
                "method": "dump_telemetry",
                "params": {"spans": 0, "gossip": 1},
            }
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        out = json.load(resp)
    if "error" in out:
        raise RuntimeError(out["error"])
    view = (out["result"] or {}).get("gossip") or {}
    return view


def load_dumps(paths: list[str]) -> list[dict]:
    """Read gossip views from saved JSON files: either a bare view (the
    `gossip` object) or a whole dump_telemetry result embedding one.
    Globs expand; unreadable/unparsable files are skipped."""
    out: list[dict] = []
    expanded: list[str] = []
    for p in paths:
        hits = sorted(glob_mod.glob(p))
        expanded.extend(hits if hits else [p])
    for path in expanded:
        try:
            with open(path, "r", encoding="utf-8") as f:
                dump = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(dump, dict):
            continue
        if "channels" in dump and "redundant" in dump:
            out.append(dump)
        elif isinstance(dump.get("gossip"), dict):
            out.append(dump["gossip"])
        elif isinstance(dump.get("result"), dict) and isinstance(
            dump["result"].get("gossip"), dict
        ):
            out.append(dump["result"]["gossip"])
    return out


def _node_label(view: dict, idx: int) -> str:
    return view.get("moniker") or (view.get("node_id") or f"node{idx}")[:12]


def build_report(views: list[dict], placement: list[str] | None = None) -> dict:
    """The structured report over N nodes' gossip views: the channel
    waterfall, the redundancy ranking, the propagation matrix, and the
    verdict naming the top waste source in wasted bytes.

    `placement` is the `testing/topology.py` region list, index-aligned
    with `views` (input order); without it every node is its own
    "region", so the matrix is node-to-node."""
    regions = [
        (placement[i] if placement and i < len(placement)
         else _node_label(v, i))
        for i, v in enumerate(views)
    ]

    chans: dict[str, dict] = {}
    kinds: dict[str, dict] = {}
    red: dict[str, dict] = {}
    for v in views:
        for c, st in (v.get("channels") or {}).items():
            agg = chans.setdefault(
                c, {"send_msgs": 0, "send_bytes": 0,
                    "recv_msgs": 0, "recv_bytes": 0},
            )
            for f in agg:
                agg[f] += st.get(f, 0)
        for k, st in (v.get("kinds") or {}).items():
            agg = kinds.setdefault(
                k, {"send_msgs": 0, "send_bytes": 0,
                    "recv_msgs": 0, "recv_bytes": 0},
            )
            for f in agg:
                agg[f] += st.get(f, 0)
        for k, st in (v.get("redundant") or {}).items():
            agg = red.setdefault(k, {"msgs": 0, "bytes": 0})
            agg["msgs"] += st.get("msgs", 0)
            agg["bytes"] += st.get("bytes", 0)

    total_bytes = sum(
        st["send_bytes"] + st["recv_bytes"] for st in chans.values()
    )

    redundancy = {}
    for k, st in red.items():
        wire = kinds.get(_WIRE_KIND.get(k, k), {})
        recv = wire.get("recv_msgs", 0)
        useful = recv - st["msgs"]
        if useful > 0:
            factor = round(recv / useful, 3)
        elif st["msgs"]:
            factor = float(st["msgs"] + 1)
        else:
            factor = 1.0
        redundancy[k] = {
            "redundant_msgs": st["msgs"],
            "redundant_bytes": st["bytes"],
            "recv_msgs": recv,
            "factor": factor,
        }

    # -- propagation matrix: first-seen deltas, origin = earliest stamp
    stamps: dict[str, list[tuple[int, float]]] = {}
    for i, v in enumerate(views):
        for key, t in (v.get("first_seen") or {}).items():
            stamps.setdefault(key, []).append((i, float(t)))
    cells: dict[tuple[str, str], list] = {}  # (from, to) -> [n, sum_ms, max_ms]
    merged_keys = 0
    for key, arr in stamps.items():
        if len(arr) < 2:
            continue
        merged_keys += 1
        origin_i, t0 = min(arr, key=lambda p: p[1])
        for i, t in arr:
            if i == origin_i:
                continue
            ms = (t - t0) * 1000.0
            cell = cells.setdefault((regions[origin_i], regions[i]), [0, 0.0, 0.0])
            cell[0] += 1
            cell[1] += ms
            cell[2] = max(cell[2], ms)
    propagation = {
        f"{a}->{b}": {
            "n": n,
            "mean_ms": round(s / n, 3),
            "max_ms": round(mx, 3),
        }
        for (a, b), (n, s, mx) in sorted(cells.items())
    }

    # -- verdict: wasted redundant bytes first; if nothing duplicates,
    # the hottest channel's concentration is the scaling story
    verdict = None
    if views:
        top_red = max(
            red.items(), key=lambda kv: kv[1]["bytes"], default=None
        )
        if top_red and top_red[1]["bytes"] > 0:
            source = _KIND_FIX.get(top_red[0], "vote_redundancy")
            cost = top_red[1]["bytes"]
        else:
            hot = max(
                chans.items(),
                key=lambda kv: kv[1]["send_bytes"] + kv[1]["recv_bytes"],
                default=None,
            )
            source = _CHANNEL_FIX.get(hot[0] if hot else "", "vote_bandwidth")
            cost = (
                hot[1]["send_bytes"] + hot[1]["recv_bytes"] if hot else 0
            )
        verdict = {
            "top_waste_source": source,
            "cost_bytes": cost,
            "fix_first": _FIXES[source],
            "reseed_note": (
                "re-run this report on the ROADMAP item 5 scaled "
                "scenario before and after the item 3 aggregation "
                "lane lands — the redundancy factors here are its "
                "before numbers"
            ),
        }
    return {
        "nodes": len(views),
        "regions": regions,
        "total_bytes": total_bytes,
        "channels": chans,
        "kinds": kinds,
        "redundancy": redundancy,
        "propagation": propagation,
        "propagation_keys_merged": merged_keys,
        "verdict": verdict,
    }


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n}B"


def render_text(report: dict) -> str:
    """The operator-facing waterfall + matrix + verdict."""
    out = [
        f"gossip observatory — {report['nodes']} node(s), "
        f"{_fmt_bytes(report['total_bytes'])} on the wire "
        f"(regions: {', '.join(dict.fromkeys(report['regions'])) or '-'})",
        "",
        "per-channel bandwidth waterfall:",
        f"{'channel':<14} {'sent':>10} {'recvd':>10} {'msgs':>9} {'share%':>7}",
    ]
    total = max(report["total_bytes"], 1)
    for c, st in sorted(
        report["channels"].items(),
        key=lambda kv: -(kv[1]["send_bytes"] + kv[1]["recv_bytes"]),
    ):
        both = st["send_bytes"] + st["recv_bytes"]
        out.append(
            f"{c:<14} {_fmt_bytes(st['send_bytes']):>10} "
            f"{_fmt_bytes(st['recv_bytes']):>10} "
            f"{st['send_msgs'] + st['recv_msgs']:>9} "
            f"{100.0 * both / total:>6.1f}%"
        )
    out.append("")
    out.append("redundancy ranking (duplicate deliveries dedup'd on arrival):")
    if report["redundancy"]:
        out.append(
            f"{'kind':<12} {'dup msgs':>9} {'dup bytes':>10} "
            f"{'recv msgs':>10} {'factor':>7}"
        )
        for k, st in sorted(
            report["redundancy"].items(),
            key=lambda kv: -kv[1]["redundant_bytes"],
        ):
            out.append(
                f"{k:<12} {st['redundant_msgs']:>9} "
                f"{_fmt_bytes(st['redundant_bytes']):>10} "
                f"{st['recv_msgs']:>10} {st['factor']:>6.2f}x"
            )
    else:
        out.append("  (no duplicate deliveries recorded)")
    out.append("")
    out.append(
        "propagation (origin region -> region, first-seen deltas over "
        f"{report['propagation_keys_merged']} merged keys):"
    )
    if report["propagation"]:
        for pair, st in report["propagation"].items():
            out.append(
                f"  {pair:<28} mean {st['mean_ms']:>8.1f}ms  "
                f"max {st['max_ms']:>8.1f}ms  (n={st['n']})"
            )
    else:
        out.append(
            "  (no cross-node stamps merged — need >= 2 nodes' dumps "
            "covering the same heights)"
        )
    verdict = report.get("verdict")
    out.append("")
    if verdict:
        out.append(
            f"verdict: top waste source is {verdict['top_waste_source']} "
            f"({_fmt_bytes(verdict['cost_bytes'])}) — {verdict['fix_first']}"
        )
        out.append(f"         {verdict['reseed_note']}")
    else:
        out.append("verdict: no gossip views collected (rollup sampled out?)")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--rpc",
        action="append",
        default=[],
        help="host:port of a live node's RPC listener (repeatable)",
    )
    ap.add_argument(
        "--dumps",
        nargs="+",
        default=[],
        help="saved dump_telemetry JSON files / bare gossip views (globs ok)",
    )
    ap.add_argument(
        "--placement",
        default="",
        help="comma-separated region labels, index-aligned with the "
        "inputs (--rpc first, then --dumps) — the testing/topology.py "
        "placement list; default: per-node labels",
    )
    ap.add_argument(
        "--json", dest="json_out", default="", help="write the structured report here"
    )
    args = ap.parse_args(argv)
    if not args.rpc and not args.dumps:
        ap.error("need --rpc and/or --dumps inputs")

    views: list[dict] = []
    for addr in args.rpc:
        views.append(fetch_gossip_rpc(addr))
    views.extend(load_dumps(args.dumps))
    placement = (
        [r.strip() for r in args.placement.split(",") if r.strip()]
        if args.placement
        else None
    )
    report = build_report(views, placement)
    print(render_text(report))
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
        print(f"\nreport -> {args.json_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
