"""State-sync demo: snapshot restore under injected chunk corruption.

Spins up an in-process validator network serving snapshots, lets it
commit past a snapshot interval, CORRUPTS a stored chunk on one serving
node (so a syncing peer receives garbage it must detect and re-fetch
elsewhere), then boots a fresh node with `state_sync` enabled and
times the restore:

    JAX_PLATFORMS=cpu python tools/statesync_demo.py
    python tools/statesync_demo.py --nodes 4 --interval 5 --chunk-size 4096

Prints discovery/restore/parity timings plus the exported
`tendermint_statesync_*` telemetry the run produced — the same series
`tools/bench_hotpath.py --statesync` folds into BENCH_hotpath.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def telemetry(name, **labels):
    from tendermint_tpu.telemetry import REGISTRY

    return REGISTRY.counter_value(name, **labels)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--nodes", type=int, default=2, help="serving validators")
    ap.add_argument("--interval", type=int, default=3, help="snapshot every N heights")
    ap.add_argument("--chunk-size", type=int, default=1024)
    ap.add_argument("--height", type=int, default=5, help="serve height before joining")
    ap.add_argument("--no-corruption", action="store_true")
    args = ap.parse_args(argv)

    from tendermint_tpu.testing.nemesis import FullNemesisNode, Nemesis

    def serving(cfg):
        cfg.statesync.snapshot_interval = args.interval
        cfg.statesync.chunk_size = args.chunk_size

    home = tempfile.mkdtemp(prefix="statesync-demo-")
    t0 = time.perf_counter()
    with Nemesis(
        args.nodes,
        home=home,
        node_factory=Nemesis.full_node_factory(config_mutator=serving),
    ) as net:
        net.nodes[0].node.mempool.check_tx(b"demo-key=demo-val")
        net.wait_height(args.height, timeout=120)
        t_chain = time.perf_counter() - t0
        manifests = net.nodes[0].node.snapshot_store.list_manifests()
        print(
            f"chain at height {max(net.heights())} in {t_chain:.1f}s; "
            f"snapshots: {[(m.height, m.chunks) for m in manifests]}"
        )

        corrupted = 0
        if not args.no_corruption and args.nodes > 1:
            # freeze snapshot-taking so the corrupted snapshot stays the
            # newest one on offer, then flip EVERY stored chunk on one
            # serving node — whatever it is asked for, the joiner must
            # blame it, drop it, and re-fetch from the honest peers
            for n in net.nodes:
                n.node.statesync_reactor.snapshot_interval = 0
            evil = net.nodes[1].node.snapshot_store
            for m in evil.list_manifests():
                for i in range(m.chunks):
                    if evil.corrupt_chunk(m.height, m.format, i):
                        corrupted += 1
            print(f"corrupted {corrupted} stored chunk(s) on node 1")

        def joining(cfg):
            cfg.statesync.enable = True
            cfg.statesync.chunk_size = args.chunk_size

        t1 = time.perf_counter()
        joiner = FullNemesisNode(
            args.nodes,
            net.genesis,
            net.privs,
            net.home,
            net.chain_id,
            config_mutator=joining,
        )
        net.add_node(joiner)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if joiner.node.statesync_reactor.restored_state is not None:
                break
            time.sleep(0.05)
        restored = joiner.node.statesync_reactor.restored_state
        if restored is None:
            print("RESTORE FAILED (gave up; fell back to fast-sync)")
            return 1
        t_restore = time.perf_counter() - t1
        target = max(n.store.height for n in net.nodes[: args.nodes])
        while joiner.store.height < target and time.monotonic() < deadline:
            time.sleep(0.05)
        t_parity = time.perf_counter() - t1
        assert joiner.app._data.get(b"demo-key") == b"demo-val"

        out = {
            "snapshot_height": joiner.node.statesync_reactor.restored_manifest.height,
            "synced_height": joiner.store.height,
            "store_base": joiner.store.base,
            "restore_s": round(t_restore, 3),
            "parity_s": round(t_parity, 3),
            "chunks_ok": telemetry("tendermint_statesync_chunks_total", result="ok"),
            "chunks_corrupt": telemetry(
                "tendermint_statesync_chunks_total", result="corrupt"
            ),
            "chunks_served": telemetry("tendermint_statesync_chunks_served_total"),
            "snapshots_taken": telemetry(
                "tendermint_statesync_snapshots_taken_total"
            ),
            "restores_ok": telemetry(
                "tendermint_statesync_restores_total", result="ok"
            ),
        }
        if corrupted and out["chunks_corrupt"] == 0:
            print("note: corrupted peer was never asked for chunk 0 this run")
        print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
