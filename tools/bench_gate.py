#!/usr/bin/env python
"""Bench regression gate: compare BENCH_hotpath.json against recorded
floors so a perf PR cannot silently regress a section it didn't mean to
touch.

The floors file (`tools/bench_floors.json`) is a list of rules over
dotted paths into the bench JSON:

    {"floors": [
      {"path": "detail.verify.host.verifies_per_s", "min": 800,
       "note": "host ed25519 floor"},
      {"path": "detail.verify.host.p99_ms", "max": 50,
       "note": "cold-start excluded from percentiles"},
      {"path": "detail.tracing_overhead.within_3pct", "truthy": true},
      {"path": "detail.mempool_ingress.speedup", "min": 3,
       "optional": true, "note": "section only present with --ingress"}
    ]}

Rules: `min` / `max` bound numeric values; `truthy` requires a true
value; `optional: true` skips (instead of failing) when the path is
missing or null — for sections that only exist on some bench shapes
(`--ingress`, `--mesh` on real silicon). Floors are deliberately set
with headroom below the seeded numbers: the gate catches step-function
regressions (a lost optimization, an accidental sync path), not CI
machine noise.

    python tools/bench_gate.py                       # repo defaults
    python tools/bench_gate.py --bench BENCH_hotpath.json \\
        --floors tools/bench_floors.json

Exit codes: 0 all rules hold, 1 regression(s), 2 usage/input error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def resolve(obj, path: str):
    """Walk a dotted path through dicts (and integer list indices);
    returns (found, value)."""
    cur = obj
    for part in path.split("."):
        if isinstance(cur, dict):
            if part not in cur:
                return False, None
            cur = cur[part]
        elif isinstance(cur, list):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                return False, None
        else:
            return False, None
    return True, cur


def check_rule(bench: dict, rule: dict) -> tuple[str, str]:
    """Evaluate one floor rule; returns (status, message) with status
    in {"ok", "skip", "fail"}."""
    path = rule.get("path", "")
    note = f"  ({rule['note']})" if rule.get("note") else ""
    found, value = resolve(bench, path)
    if not found or value is None:
        if rule.get("optional"):
            return "skip", f"SKIP {path}: absent (optional){note}"
        return "fail", f"FAIL {path}: missing from bench output{note}"
    if rule.get("truthy"):
        if bool(value):
            return "ok", f"OK   {path} = {value!r}{note}"
        return "fail", f"FAIL {path} = {value!r}, expected truthy{note}"
    try:
        num = float(value)
    except (TypeError, ValueError):
        return "fail", f"FAIL {path} = {value!r}, not numeric{note}"
    lo, hi = rule.get("min"), rule.get("max")
    if lo is not None and num < float(lo):
        return "fail", f"FAIL {path} = {num} < floor {lo}{note}"
    if hi is not None and num > float(hi):
        return "fail", f"FAIL {path} = {num} > ceiling {hi}{note}"
    bounds = []
    if lo is not None:
        bounds.append(f">= {lo}")
    if hi is not None:
        bounds.append(f"<= {hi}")
    return "ok", f"OK   {path} = {num} ({', '.join(bounds) or 'no bound'}){note}"


def run_gate(bench: dict, floors: dict) -> tuple[bool, list[str]]:
    rules = floors.get("floors", [])
    lines: list[str] = []
    failed = 0
    for rule in rules:
        status, msg = check_rule(bench, rule)
        lines.append(msg)
        if status == "fail":
            failed += 1
    lines.append(
        f"{len(rules)} rules: {len(rules) - failed} held, {failed} regressed"
    )
    return failed == 0, lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--bench", default=os.path.join(_REPO, "BENCH_hotpath.json")
    )
    ap.add_argument(
        "--floors", default=os.path.join(_REPO, "tools", "bench_floors.json")
    )
    ap.add_argument(
        "-q", "--quiet", action="store_true", help="print failures only"
    )
    args = ap.parse_args(argv)
    try:
        with open(args.bench, "r", encoding="utf-8") as f:
            bench = json.load(f)
    except (OSError, ValueError) as e:
        sys.stderr.write(f"bench_gate: cannot read {args.bench}: {e}\n")
        return 2
    try:
        with open(args.floors, "r", encoding="utf-8") as f:
            floors = json.load(f)
    except (OSError, ValueError) as e:
        sys.stderr.write(f"bench_gate: cannot read {args.floors}: {e}\n")
        return 2
    ok, lines = run_gate(bench, floors)
    for line in lines:
        if not args.quiet or line.startswith("FAIL") or line is lines[-1]:
            print(line)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
