"""Device report: the per-launch kernel waterfall.

Merges N nodes' launch ledgers (`telemetry/launchlog.py` — one record
per device launch with backend, mesh width, useful/padded/cached rows,
stage durations, transfer bytes, and compile-cache attribution) into
one per-kind waterfall and **names the top waste source**: padding
waste (zero rows shipped for bucket/mesh geometry), compile stalls
(`_STEP_CACHE` misses), transfer overhead (sharded-table `device_put`
re-ships), or launch-gap idle (device sitting between launches). The
device twin of `tools/contention_report.py`.

This is where the ROADMAP **real-silicon reseed** bullet starts: run a
loadgen net on the real TPU pod, pull the ledgers, and fix the named
source first — the verdict is also the measured cost model ROADMAP
items 2 (device-native state tree) and 5 (BLS aggregation lane) must
be judged against.

    # against live nodes (one --rpc per node)
    python tools/device_report.py --rpc 127.0.0.1:26657 --rpc 127.0.0.1:26660

    # from persisted ledgers / flight-embedded dumps
    python tools/device_report.py --ledgers node*/data/launches.jsonl

Output: a text waterfall per launch kind (occupancy %, padding waste %,
cache-withheld %, stage split, transfer, compile amortization), the
consumer mix, and the fix-first-on-silicon verdict. `--json` writes the
structured report.
"""

from __future__ import annotations

import argparse
import glob as glob_mod
import json
import sys
import urllib.request

import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tendermint_tpu.telemetry.launchlog import summarize

# waste sources the verdict can name, with the ROADMAP pointer each
# one implies on real silicon
_FIXES = {
    "padding_waste": (
        "shrink the ops/padding.py bucket ladder (or align batch/valset "
        "sizes to the mesh) — wasted device-seconds scale with every "
        "perf item, including items 2 and 5"
    ),
    "compile_stalls": (
        "warm the persistent XLA cache (utils/jax_cache.py) and pre-"
        "compile the mesh steps at boot — a survivor re-mesh or valset "
        "rotation must not stall launches"
    ),
    "transfer_overhead": (
        "grow the sharded-table placement cache or shrink table bytes "
        "per chip — the device_put re-ship is the cost model item 5's "
        "BLS lane must beat"
    ),
    "launch_gap_idle": (
        "widen the coalescer window / raise dispatch depth — the device "
        "is starved between launches, not slow inside them (the item 2 "
        "incremental state tree adds launches to fill these gaps)"
    ),
}


def fetch_launches_rpc(addr: str, n: int = 512, timeout: float = 30.0) -> list[dict]:
    """dump_telemetry(launches=N) over JSON-RPC; returns the records."""
    req = urllib.request.Request(
        f"http://{addr}/",
        data=json.dumps(
            {
                "jsonrpc": "2.0",
                "id": 1,
                "method": "dump_telemetry",
                "params": {"spans": 0, "launches": int(n)},
            }
        ).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        out = json.load(resp)
    if "error" in out:
        raise RuntimeError(out["error"])
    view = (out["result"] or {}).get("launches") or {}
    return view.get("records") or []


def load_ledgers(paths: list[str]) -> list[dict]:
    """Read launch records from JSONL ledgers (`launches.jsonl`), from
    `launchledger-*.json` dumps, or from flight-recorder dumps (their
    embedded `launches` key). Duplicates across overlapping inputs
    dedupe on (t, kind, rows, queue)."""
    out: list[dict] = []
    seen: set = set()
    expanded: list[str] = []
    for p in paths:
        hits = sorted(glob_mod.glob(p))
        expanded.extend(hits if hits else [p])
    for path in expanded:
        records: list[dict] = []
        try:
            with open(path, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        try:
            # a whole-file JSON document: launchledger-*.json /
            # flightrec-*.json dump with an embedded record list
            dump = json.loads(text)
            if isinstance(dump, dict):
                records = dump.get("records") or dump.get("launches") or []
        except ValueError:
            # JSONL ledger: one record per line, torn tails skipped
            for line in text.splitlines():
                try:
                    d = json.loads(line)
                except ValueError:
                    continue
                if isinstance(d, dict):
                    records.append(d)
        for r in records:
            if not isinstance(r, dict) or "kind" not in r:
                continue
            key = (r.get("t"), r.get("kind"), r.get("rows"), r.get("queue"))
            if key in seen:
                continue
            seen.add(key)
            out.append(r)
    out.sort(key=lambda r: r.get("t", 0.0))
    return out


def _launch_gaps(records: list[dict]) -> dict:
    """Idle seconds between consecutive launches per (node, queue) —
    launch start approximated as commit wall time minus total_s. Only
    queue-bearing records participate (synchronous implicit launches
    have no queue to idle)."""
    lanes: dict[tuple, list[tuple[float, float]]] = {}
    for r in records:
        q = r.get("queue")
        if not q:
            continue
        t_end = float(r.get("t", 0.0))
        t_start = t_end - float(r.get("total_s", 0.0))
        lanes.setdefault((r.get("node", ""), q), []).append((t_start, t_end))
    idle_s = 0.0
    busy_s = 0.0
    gaps = 0
    for spans in lanes.values():
        spans.sort()
        prev_end = None
        for t_start, t_end in spans:
            busy_s += max(0.0, t_end - t_start)
            if prev_end is not None and t_start > prev_end:
                idle_s += t_start - prev_end
                gaps += 1
            prev_end = max(prev_end or t_end, t_end)
    return {
        "idle_s": round(idle_s, 6),
        "busy_s": round(busy_s, 6),
        "gaps": gaps,
        "lanes": len(lanes),
    }


def build_report(records: list[dict]) -> dict:
    """The structured report: the per-kind waterfall (shared rollup
    from telemetry/launchlog.py, so live dumps and offline merges can
    never disagree), the launch-gap analysis, and the verdict naming
    the top waste source in device-seconds."""
    kinds = summarize(records)
    gapinfo = _launch_gaps(records)

    total_in_flight = sum(k["stages_s"]["in_flight"] for k in kinds.values())
    total_rows = sum(k["rows"] for k in kinds.values())
    total_padded = sum(k["rows_padded"] for k in kinds.values())
    shipped = total_rows + total_padded
    waste = {
        # device-seconds the pad rows occupied: in-flight time scaled
        # by the padded share of shipped rows
        "padding_waste": round(
            total_in_flight * (total_padded / shipped) if shipped else 0.0, 6
        ),
        "compile_stalls": round(
            sum(k["compile_s"] for k in kinds.values()), 6
        ),
        "transfer_overhead": round(
            sum(k["device_put_s"] for k in kinds.values()), 6
        ),
        "launch_gap_idle": gapinfo["idle_s"],
    }
    verdict = None
    if records:
        top = max(waste, key=lambda k: waste[k])
        verdict = {
            "top_waste_source": top,
            "cost_s": waste[top],
            "fix_first_on_silicon": _FIXES[top],
            "reseed_note": (
                "reseed BENCH_hotpath.json device sections from this "
                "report on the real pod (ROADMAP real-silicon reseed "
                "bullet); the per-kind costs here are the launch cost "
                "model for ROADMAP items 2 and 5"
            ),
        }
    return {
        "launches": len(records),
        "nodes": sorted({r.get("node", "") for r in records if r.get("node")}),
        "kinds": kinds,
        "launch_gaps": gapinfo,
        "waste_s": waste,
        "verdict": verdict,
    }


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n}B"


def render_text(report: dict) -> str:
    """The operator-facing waterfall."""
    out = [
        "device observatory — per-kind launch waterfall "
        f"({report['launches']} launches"
        + (
            f", nodes: {', '.join(n[:12] for n in report['nodes'])}"
            if report["nodes"]
            else ""
        )
        + ")",
        "",
        f"{'kind':<12} {'launches':>8} {'rows':>9} {'occup%':>7} "
        f"{'pad%':>6} {'cached%':>8} {'transfer':>10} {'compile':>9}",
    ]
    for kind, agg in sorted(
        report["kinds"].items(), key=lambda kv: -kv[1]["launches"]
    ):
        occ = agg["occupancy_pct"]
        pad = agg["padding_waste_pct"]
        cached = agg["cache_withheld_pct"]
        out.append(
            f"{kind:<12} {agg['launches']:>8} {agg['rows']:>9} "
            f"{occ if occ is not None else '-':>7} "
            f"{pad if pad is not None else '-':>6} "
            f"{cached if cached is not None else '-':>8} "
            f"{_fmt_bytes(agg['transfer_bytes']):>10} "
            f"{agg['compile_misses']}m/{agg['compile_hits']}h"
        )
        st = agg["stages_s"]
        out.append(
            f"{'':12} stages: queue_wait {st['queue_wait']:.3f}s | "
            f"host_prep {st['host_prep']:.3f}s | in_flight "
            f"{st['in_flight']:.3f}s | finalize {st['finalize']:.3f}s"
            + (
                f" | compile {agg['compile_s']:.3f}s"
                if agg["compile_s"]
                else ""
            )
            + (
                f" | device_put {agg['device_put_s']:.3f}s"
                if agg["device_put_s"]
                else ""
            )
        )
        if agg["consumers"]:
            mix = ", ".join(
                f"{c} {n}"
                for c, n in sorted(
                    agg["consumers"].items(), key=lambda kv: -kv[1]
                )
            )
            out.append(f"{'':12} consumers: {mix}")
    gaps = report["launch_gaps"]
    out.append("")
    out.append(
        f"launch gaps: {gaps['idle_s']:.3f}s idle vs {gaps['busy_s']:.3f}s "
        f"busy across {gaps['lanes']} queue lane(s) ({gaps['gaps']} gaps)"
    )
    out.append(
        "waste (device-seconds): "
        + ", ".join(f"{k} {v:.3f}s" for k, v in report["waste_s"].items())
    )
    verdict = report.get("verdict")
    if verdict:
        out.append(
            f"verdict: top waste source is {verdict['top_waste_source']} "
            f"({verdict['cost_s']:.3f}s) — {verdict['fix_first_on_silicon']}"
        )
        out.append(f"         {verdict['reseed_note']}")
    else:
        out.append("verdict: no launches recorded (is the ledger enabled?)")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--rpc",
        action="append",
        default=[],
        help="host:port of a live node's RPC listener (repeatable)",
    )
    ap.add_argument(
        "--ledgers",
        nargs="+",
        default=[],
        help="launches.jsonl / launchledger-*.json / flightrec-*.json (globs ok)",
    )
    ap.add_argument(
        "--launches",
        type=int,
        default=512,
        help="records to pull per --rpc node",
    )
    ap.add_argument(
        "--json", dest="json_out", default="", help="write the structured report here"
    )
    args = ap.parse_args(argv)
    if not args.rpc and not args.ledgers:
        ap.error("need --rpc and/or --ledgers inputs")

    records: list[dict] = []
    seen: set = set()
    for addr in args.rpc:
        # dedupe across sources: multi-node-in-process harnesses serve
        # the same process-wide ledger from every node's RPC
        for r in fetch_launches_rpc(addr, n=args.launches):
            key = (r.get("t"), r.get("kind"), r.get("rows"), r.get("queue"))
            if key in seen:
                continue
            seen.add(key)
            records.append(r)
    if args.ledgers:
        for r in load_ledgers(args.ledgers):
            key = (r.get("t"), r.get("kind"), r.get("rows"), r.get("queue"))
            if key not in seen:
                seen.add(key)
                records.append(r)
    records.sort(key=lambda r: r.get("t", 0.0))
    report = build_report(records)
    print(render_text(report))
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
        print(f"\nreport -> {args.json_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
