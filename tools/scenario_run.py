"""Run declarative chaos scenarios from the library or a JSON spec.

Usage:
    JAX_PLATFORMS=cpu python tools/scenario_run.py --list
    JAX_PLATFORMS=cpu python tools/scenario_run.py --scenario churn_small
    JAX_PLATFORMS=cpu python tools/scenario_run.py --all --json /tmp/reports.json
    JAX_PLATFORMS=cpu python tools/scenario_run.py --spec my_scenario.json

Each scenario spins up an in-process Nemesis network, applies the
declared WAN topology / churn schedule / fault timeline / load
profile, grades the run against the spec's `expect` block (finality
SLOs, epoch counts, adaptive-timeout convergence, bisection bridging),
and prints a per-scenario report. Exits non-zero if any scenario
fails its invariants or expectations.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt_finality(fin: dict) -> str:
    if not fin or not fin.get("count"):
        return "no finality samples"
    return (
        f"finality p50={fin['p50_s']:.2f}s p95={fin['p95_s']:.2f}s "
        f"max={fin['max_s']:.2f}s over {fin['count']} heights"
    )


def _detail(report: dict) -> str:
    bits = [f"heights {report['heights']}", _fmt_finality(report["finality"])]
    if "epochs" in report:
        bits.append(
            f"epochs={report['epochs']} rebuilds={report['valset_rebuilds']}"
        )
    if "bisection" in report:
        b = report["bisection"]
        bits.append(
            f"bisected to h{b['verified_to']} in {b['rounds']} rounds"
        )
    if "propose_timeout_s" in report:
        bits.append(
            f"propose timeout {report['propose_timeout_s']['min']:.3f}s "
            f"> one-way delay {report['max_one_way_delay_s']:.3f}s"
        )
    skips = report.get("round_skips_post_warm")
    if skips is not None:
        bits.append(f"post-warm skips={skips}")
    return ", ".join(bits)


def _fmt_mb(n: float) -> str:
    return f"{n / 1e6:.2f}MB" if n >= 1e5 else f"{n / 1e3:.1f}kB"


def _print_gossip_table(report: dict) -> None:
    """The gossip verdict table (bandwidth per channel, redundancy
    factor per kind) from the scenario's fleet-wide rollup — printed
    alongside the finality report so over-gossip is visible in the same
    place as slow finality."""
    g = report.get("gossip")
    if not g:
        return
    chans = ", ".join(
        f"{c} {_fmt_mb(b)}"
        for c, b in sorted(
            g["channel_bytes"].items(), key=lambda kv: -kv[1]
        )[:5]
    )
    print(f"    gossip: {_fmt_mb(g['total_bytes'])} on the wire — {chans}")
    if g["redundancy_factor"]:
        factors = ", ".join(
            f"{k} {f:.2f}x ({_fmt_mb(g['redundant'][k]['bytes'])} dup)"
            for k, f in sorted(
                g["redundancy_factor"].items(), key=lambda kv: -kv[1]
            )
        )
        top = g.get("top_redundant_kind")
        print(
            f"    redundancy: {factors}"
            + (f" — top waste: {top}" if top else "")
        )


def main() -> int:
    from tendermint_tpu.testing.scenario import (
        SCENARIO_LIBRARY,
        ScenarioRunner,
        validate_scenario,
    )
    from tendermint_tpu.utils.log import setup_logging

    ap = argparse.ArgumentParser(
        description="declarative chaos scenario runner"
    )
    ap.add_argument("--list", action="store_true", help="list library scenarios")
    ap.add_argument(
        "--scenario",
        action="append",
        default=None,
        metavar="NAME",
        help="run this library scenario (repeatable)",
    )
    ap.add_argument(
        "--all", action="store_true", help="run the entire library, slow included"
    )
    ap.add_argument(
        "--spec",
        metavar="PATH",
        default=None,
        help="run a scenario spec from a JSON file instead of the library",
    )
    ap.add_argument(
        "--json",
        metavar="PATH",
        default=None,
        help="also write the full reports as JSON here",
    )
    ap.add_argument("--home", default=None, help="scratch dir (default: tmp)")
    args = ap.parse_args()

    if args.list:
        width = max(len(n) for n in SCENARIO_LIBRARY)
        for name, spec in SCENARIO_LIBRARY.items():
            tier = "slow" if spec.get("slow", True) else "tier-1"
            print(f"  {name:<{width}}  [{tier:>6}]  {spec['description']}")
        return 0

    setup_logging("scenario:info,nemesis:warning,*:error")

    specs: list[dict] = []
    if args.spec:
        with open(args.spec) as fh:
            specs.append(validate_scenario(json.load(fh)))
    elif args.all:
        specs = [dict(s) for s in SCENARIO_LIBRARY.values()]
    elif args.scenario:
        for name in args.scenario:
            if name not in SCENARIO_LIBRARY:
                ap.error(
                    f"unknown scenario {name!r} — choices: "
                    f"{', '.join(SCENARIO_LIBRARY)}"
                )
            specs.append(dict(SCENARIO_LIBRARY[name]))
    else:
        ap.error("pick --list, --scenario NAME, --all, or --spec PATH")

    home = args.home or tempfile.mkdtemp(prefix="scenario-run-")
    reports = []
    for spec in specs:
        print(f"=== {spec['name']}: {spec.get('description', '')}")
        report = ScenarioRunner(home=home).run(spec)
        reports.append(report)
        verdict = "PASS" if report["ok"] else "FAIL"
        print(f"    {verdict} in {report['elapsed_s']}s — {_detail(report)}")
        _print_gossip_table(report)
        for failure in report["failures"]:
            print(f"    failure: {failure}")

    if args.json:
        with open(args.json, "w") as fh:
            json.dump(reports, fh, indent=2, sort_keys=True)
        print(f"reports written to {args.json}")

    width = max(len(r["scenario"]) for r in reports)
    failed = 0
    print("\nscenario results:")
    for report in reports:
        verdict = "PASS" if report["ok"] else "FAIL"
        print(f"  {report['scenario']:<{width}}  {verdict}  {_detail(report)}")
        failed += not report["ok"]
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
