"""Open-loop mempool load generator (ROADMAP open item 2) + read traffic.

Drives CheckTx traffic the way "millions of users" would: arrivals are
scheduled by a fixed-rate open-loop process (a slow mempool does NOT
slow the generator down — backlog shows up as admission latency, the
honest serving metric per ACE-style sub-second targets), spread over N
client threads, with configurable payload size, hot-key skew, and
duplicate re-sends (gossip-style re-arrivals that should be near-free
through the dup cache / VerifiedSigCache).

Two write targets:

* in-process (default): builds a KVStore app + the production mempool
  shape (sharded lanes + ingress batching over `default_verifier()`),
  then reads latency back from the same
  `tendermint_mempool_admission_seconds` histogram a node exports;
* `--rpc host:port`: fires `broadcast_tx_sync` at a running node.

`--reads` flips the generator into light-client QUERY traffic against
a replica fleet (`--rpc host:port[,host:port...]`, round-robin):
proof reads (`full_commit` / `commit` / `validators`) with hot-height
skew — recent heights are what real users hammer — plus a
`--walk-prob` fraction of full verify-to-height walks, each a FRESH
`BisectingCertifier` bootstrapping from the genesis pin through the
target's proofs (the "new light client joins" workload). The bench and
nemesis replica scenarios drive this mode.

    JAX_PLATFORMS=cpu python tools/loadgen.py --rate 20000 --duration 3
    python tools/loadgen.py --rate 100000 --threads 16 --signed  # TPU
    python tools/loadgen.py --rpc 127.0.0.1:46657 --rate 500
    python tools/loadgen.py --reads --rpc 127.0.0.1:46657,127.0.0.1:46658

Output: one JSON summary line on stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentile(sorted_vals, q: float):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1)))
    return sorted_vals[idx]


class TxFactory:
    """Payload builder with hot-key and duplicate skew. Hot keys model
    many users hammering the same state (`k<i>=` collides app-side);
    duplicates model gossip re-arrivals of the SAME tx bytes."""

    def __init__(self, payload: int, hot_keys: int, hot_prob: float,
                 dup_prob: float, signed: bool, signers: int, seed: int = 7):
        self._rng = random.Random(seed)
        self._payload = max(8, payload)
        self._hot_keys = max(0, hot_keys)
        self._hot_prob = hot_prob
        self._dup_prob = dup_prob
        self._recent: list[bytes] = []
        self._recent_lock = threading.Lock()
        self._privs = []
        if signed:
            from tendermint_tpu.crypto.keys import gen_priv_key

            self._privs = [
                gen_priv_key(bytes([i % 256]) * 32) for i in range(max(1, signers))
            ]

    def make(self, n: int) -> bytes:
        rng = self._rng
        if self._dup_prob > 0 and rng.random() < self._dup_prob:
            with self._recent_lock:
                if self._recent:
                    return self._recent[rng.randrange(len(self._recent))]
        if self._hot_keys and rng.random() < self._hot_prob:
            key = b"hot%d" % rng.randrange(self._hot_keys)
        else:
            key = b"k%d" % n
        body = b"%s=%d;" % (key, n)
        body += b"x" * max(0, self._payload - len(body))
        if self._privs:
            from tendermint_tpu.mempool.ingress import make_signed_tx

            tx = make_signed_tx(self._privs[n % len(self._privs)], body)
        else:
            tx = body
        with self._recent_lock:
            self._recent.append(tx)
            if len(self._recent) > 4096:
                self._recent.pop(0)
        return tx


class Stats:
    def __init__(self):
        self.lock = threading.Lock()
        self.outcomes: dict[str, int] = {}
        self.latencies: list[float] = []
        self.late_arrivals = 0

    def record(self, outcome: str, latency_s: float) -> None:
        with self.lock:
            self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
            self.latencies.append(latency_s)


def _outcome(code: int) -> str:
    return {0: "ok", 4: "bad_sig", 5: "duplicate"}.get(code, "rejected")


def run_inprocess(args, factory: TxFactory, stats: Stats):
    from tendermint_tpu.abci.apps import KVStoreApp
    from tendermint_tpu.abci.client import local_client_creator
    from tendermint_tpu.mempool import Mempool
    from tendermint_tpu.services.verifier import default_verifier

    conns = local_client_creator(KVStoreApp())()
    verifier = default_verifier()
    mp = Mempool(
        conns.mempool,
        cache_size=10_000_000,
        verifier=verifier,
        lanes=args.lanes or None,
        ingress_batch=not args.legacy,
    )

    def submit(tx, t_sched):
        def cb(res, t_sched=t_sched):
            stats.record(_outcome(res.code), time.perf_counter() - t_sched)

        mp.check_tx_async(tx, cb)

    drain = lambda: None  # noqa: E731
    return mp, submit, drain


def _rpc_get(target: str, method: str, timeout: float = 30.0, **params):
    import urllib.parse
    import urllib.request

    qs = urllib.parse.urlencode(params)
    url = f"http://{target}/{method}" + (f"?{qs}" if qs else "")
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        out = json.load(resp)
    if "error" in out and out["error"]:
        raise RuntimeError(out["error"].get("message", "rpc error"))
    return out["result"]


def run_reads(args, stats: Stats):
    """Open-loop light-client query traffic against a replica fleet."""
    targets = [t.strip() for t in args.rpc.split(",") if t.strip()]
    if not targets:
        raise SystemExit("--reads needs --rpc host:port[,host:port...]")
    st = _rpc_get(targets[0], "status")
    chain_id = st["node_info"]["chain_id"]
    tip = int(st["sync_info"]["latest_block_height"])
    gen = _rpc_get(targets[0], "genesis")["genesis"]
    rng = random.Random(11)
    rng_lock = threading.Lock()
    hot_window = max(1, min(args.hot_keys or 8, tip))

    def pick_height() -> int:
        with rng_lock:
            if args.hot_prob > 0 and rng.random() < args.hot_prob:
                return max(1, tip - rng.randrange(hot_window))
            return rng.randrange(1, tip + 1)

    def do_walk(target: str) -> str:
        """A fresh light client bootstraps from the genesis pin and
        verifies to the tip through this replica's proofs."""
        from tendermint_tpu.certifiers.node_provider import NodeProvider
        from tendermint_tpu.lightclient import BisectingCertifier
        from tendermint_tpu.rpc.client import HTTPClient
        from tendermint_tpu.types.genesis import GenesisDoc

        doc = GenesisDoc.from_json(json.dumps(gen))
        cert = BisectingCertifier(
            chain_id,
            validators=doc.validator_set(),
            height=0,
            source=NodeProvider(HTTPClient(target)),
        )
        cert.verify_to_height(tip)
        return "walk"

    def submit(n: int, t_sched: float) -> None:
        target = targets[n % len(targets)]
        with rng_lock:
            r = rng.random()
        try:
            if r < args.walk_prob:
                kind = do_walk(target)
            elif r < args.walk_prob + 0.5:
                kind = "full_commit"
                _rpc_get(target, "full_commit", height=pick_height())
            elif r < args.walk_prob + 0.75:
                kind = "commit"
                _rpc_get(target, "commit", height=pick_height())
            else:
                kind = "validators"
                _rpc_get(target, "validators", height=pick_height())
            stats.record(kind, time.perf_counter() - t_sched)
        except Exception:
            stats.record("error", time.perf_counter() - t_sched)

    return None, submit, lambda: None


def run_rpc(args, factory: TxFactory, stats: Stats):
    import urllib.request

    url = f"http://{args.rpc}/"

    def submit(tx, t_sched):
        req = urllib.request.Request(
            url,
            data=json.dumps(
                {
                    "jsonrpc": "2.0",
                    "id": 1,
                    "method": "broadcast_tx_sync",
                    "params": {"tx": tx.hex()},
                }
            ).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.load(resp)
            code = int(out.get("result", {}).get("code", 1))
            stats.record(_outcome(code), time.perf_counter() - t_sched)
        except Exception:
            stats.record("error", time.perf_counter() - t_sched)

    return None, submit, lambda: None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rate", type=float, default=5000.0, help="offered tx/s (open loop)")
    ap.add_argument("--duration", type=float, default=3.0, help="seconds of traffic")
    ap.add_argument("--threads", type=int, default=8, help="client threads")
    ap.add_argument("--payload", type=int, default=64, help="payload bytes")
    ap.add_argument("--hot-keys", type=int, default=16, dest="hot_keys",
                    help="hot-key pool size (0 disables)")
    ap.add_argument("--hot-prob", type=float, default=0.2, dest="hot_prob",
                    help="probability an arrival uses a hot key")
    ap.add_argument("--dup-prob", type=float, default=0.0, dest="dup_prob",
                    help="probability an arrival re-sends recent tx bytes")
    ap.add_argument("--signed", action="store_true",
                    help="wrap payloads in the signed-tx envelope")
    ap.add_argument("--signers", type=int, default=16)
    ap.add_argument("--lanes", type=int, default=0, help="mempool lanes (0=default)")
    ap.add_argument("--legacy", action="store_true",
                    help="ingress batching OFF (one-at-a-time admission)")
    ap.add_argument("--rpc", default="", help="host:port of a running node "
                    "(default: in-process mempool); comma-separated fleet "
                    "with --reads")
    ap.add_argument("--reads", action="store_true",
                    help="light-client query traffic (proof reads + walks) "
                    "against a replica fleet instead of CheckTx writes")
    ap.add_argument("--walk-prob", type=float, default=0.05, dest="walk_prob",
                    help="fraction of read arrivals that run a full "
                    "verify-to-height walk (fresh client bootstrap)")
    args = ap.parse_args(argv)

    factory = TxFactory(
        args.payload, args.hot_keys, args.hot_prob, args.dup_prob,
        args.signed, args.signers,
    )
    stats = Stats()
    if args.reads:
        mp, submit, drain = run_reads(args, stats)
    else:
        mp, submit, drain = (
            run_rpc(args, factory, stats) if args.rpc
            else run_inprocess(args, factory, stats)
        )

    n_total = int(args.rate * args.duration)
    interval = 1.0 / args.rate if args.rate > 0 else 0.0
    t0 = time.perf_counter() + 0.05  # shared epoch for all threads

    make = (lambda n: n) if args.reads else factory.make

    def worker(k: int):
        late = 0
        for n in range(k, n_total, args.threads):
            due = t0 + n * interval
            now = time.perf_counter()
            if due > now:
                time.sleep(due - now)
            elif now - due > 0.001:
                late += 1  # open loop: fire immediately, count the slip
            submit(make(n), due)
        with stats.lock:
            stats.late_arrivals += late

    sys.stderr.write(
        f"offering {args.rate:.0f} tx/s x {args.duration}s over "
        f"{args.threads} threads ({n_total} txs)...\n"
    )
    threads = [
        threading.Thread(target=worker, args=(k,)) for k in range(args.threads)
    ]
    wall0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # wait for in-flight admissions to resolve
    deadline = time.time() + 30
    while time.time() < deadline:
        with stats.lock:
            if len(stats.latencies) >= n_total:
                break
        time.sleep(0.05)
    wall = time.perf_counter() - wall0
    drain()
    if mp is not None:
        mp.close()

    with stats.lock:
        lat = sorted(stats.latencies)
        outcomes = dict(stats.outcomes)
        late = stats.late_arrivals
    out = {
        "offered_rate": args.rate,
        "duration_s": args.duration,
        "threads": args.threads,
        "payload_bytes": args.payload,
        "signed": bool(args.signed),
        "dup_prob": args.dup_prob,
        "hot_prob": args.hot_prob,
        "mode": "reads" if args.reads else (
            "rpc" if args.rpc else ("legacy" if args.legacy else "batched")
        ),
        "walk_prob": args.walk_prob if args.reads else None,
        "submitted": n_total,
        "resolved": len(lat),
        "achieved_checktx_per_s": round(len(lat) / wall, 1) if wall > 0 else None,
        "outcomes": outcomes,
        "late_arrivals": late,
        "admission_p50_ms": round(_percentile(lat, 0.50) * 1e3, 3) if lat else None,
        "admission_p99_ms": round(_percentile(lat, 0.99) * 1e3, 3) if lat else None,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
