"""Time the fused tables kernel on the bench device (random-valued
tables — timing is value-independent)."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.ops.ed25519_tables import verify_tables_kernel

N = 10_240


def timeit(fn, *args, reps=3, **kw):
    np.asarray(fn(*args, **kw))
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        np.asarray(fn(*args, **kw))
        best = min(best, time.time() - t0)
    return best


def main():
    rng = np.random.default_rng(0)
    tbl = jnp.asarray(
        rng.integers(0, 8192, size=(64, 16, 60, N), dtype=np.int16)
    )
    for k in (16, 32, 64):
        b = k * N
        s = jnp.asarray(rng.integers(0, 256, size=(b, 32), dtype=np.int32).astype(np.uint8))
        h = jnp.asarray(rng.integers(0, 256, size=(b, 32), dtype=np.int32).astype(np.uint8))
        r = jnp.asarray(rng.integers(0, 256, size=(b, 32), dtype=np.int32).astype(np.uint8))
        t = timeit(verify_tables_kernel, tbl, s, h, r, impl="fused")
        print(f"K={k} B={b}: fused={t*1e3:.1f}ms -> {b/t:,.0f}/s", flush=True)


if __name__ == "__main__":
    main()
