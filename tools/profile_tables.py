"""Stage-by-stage profile of the ed25519 tables verify path on the bench
device. Timing is value-independent (fixed shapes, integer ops), so tables
and lane inputs are random with in-range limb magnitudes — no 65s build.

Stages, for B = K*N lanes:
  sel    : _select_entries                (table -> (96, B, 60) entries)
  chain  : _sum_entries_pallas            (entries -> extended point)
  finish : batched invert + encode + cmp  (point -> verdict)
  full   : verify_tables_kernel
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from tendermint_tpu.ops.ed25519_kernel import NLIMBS, fe_canon, fe_carry, fe_mul, fe_to_bytes
from tendermint_tpu.ops.ed25519_tables import (
    _select_entries,
    _sum_entries_pallas,
    fe_batch_invert,
    verify_tables_kernel,
)

N = 10_240


def timeit(fn, *args, reps=3):
    """fn must return a SMALL array; sync point is the d2h fetch
    (block_until_ready does not synchronize under axon)."""
    np.asarray(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        np.asarray(fn(*args))
        best = min(best, time.time() - t0)
    return best


def finish(x, y, z, r):
    zinv = fe_batch_invert(fe_carry(z))
    x_aff = fe_canon(fe_mul(x, zinv))
    y_bytes = fe_to_bytes(fe_mul(y, zinv))
    parity = x_aff[..., 0] & 1
    sign = (r[..., 31] >> 7) & 1
    r_clean = r.at[..., 31].set(r[..., 31] & 0x7F)
    return jnp.all(y_bytes == r_clean, axis=-1) & (parity == sign)


def main():
    rng = np.random.default_rng(0)
    tbl = jnp.asarray(rng.integers(0, 8192, size=(64, 16, 60, N), dtype=np.int16))
    # each stage reduced to a scalar on device so the d2h sync is tiny
    sel_small = jax.jit(lambda t, s, h: _select_entries(t, s, h).sum())
    sel_j = jax.jit(_select_entries)
    chain_small = jax.jit(lambda e: sum(c.sum() for c in _sum_entries_pallas(e)))
    fin_j = jax.jit(finish)

    for k in (4, 16):
        b = k * N
        s = jnp.asarray(rng.integers(0, 256, size=(b, 32), dtype=np.int32).astype(np.uint8))
        h = jnp.asarray(rng.integers(0, 256, size=(b, 32), dtype=np.int32).astype(np.uint8))
        r = jnp.asarray(rng.integers(0, 256, size=(b, 32), dtype=np.int32).astype(np.uint8))
        si = s.astype(jnp.int32)
        hi = h.astype(jnp.int32)
        ri = r.astype(jnp.int32)

        t_full = timeit(verify_tables_kernel, tbl, s, h, r)
        print(f"K={k} B={b}: full={t_full*1e3:.1f}ms -> {b/t_full:,.0f}/s", flush=True)
        t_sel = timeit(sel_small, tbl, si, hi)
        print(f"K={k} B={b}: sel={t_sel*1e3:.1f}ms", flush=True)
        ent = sel_j(tbl, si, hi)
        t_chain = timeit(chain_small, ent)
        print(f"K={k} B={b}: chain={t_chain*1e3:.1f}ms", flush=True)
        x, y, z, _t = jax.jit(_sum_entries_pallas)(ent)
        t_fin = timeit(fin_j, x, y, z, ri)
        print(f"K={k} B={b}: finish={t_fin*1e3:.1f}ms", flush=True)


if __name__ == "__main__":
    main()
