"""tmlint CLI — repo-invariant static analysis for tendermint_tpu.

Usage:
    python -m tools.tmlint [paths...]            # default: tendermint_tpu/
    python -m tools.tmlint --changed             # only files differing from HEAD
    python -m tools.tmlint --rules L001,L002 p2p/
    python -m tools.tmlint --write-baseline      # grandfather current findings
    python -m tools.tmlint --list-rules

Exit codes: 0 clean, 1 findings, 2 usage/internal error.

Suppress a single finding in source with a REASONED comment on (or one
line above) the flagged line:

    with self._counter_lock:  # tmlint: disable=L001 -- snapshot only, never nested further

Reasonless suppressions are themselves findings (S001). See
docs/STATIC_ANALYSIS.md for the rule catalog and the lock-rank table.
"""

from __future__ import annotations

import argparse
import pathlib
import subprocess
import sys

_REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(_REPO))

from tendermint_tpu.analysis import engine  # noqa: E402


def changed_files(root: pathlib.Path) -> list[pathlib.Path]:
    """Python files differing from HEAD (staged, unstaged, untracked) —
    the fast pre-commit lane."""
    out: list[pathlib.Path] = []
    for args in (
        ["git", "diff", "--name-only", "HEAD", "--", "*.py"],
        ["git", "ls-files", "--others", "--exclude-standard", "--", "*.py"],
    ):
        try:
            proc = subprocess.run(
                args, cwd=root, capture_output=True, text=True, check=True
            )
        except (OSError, subprocess.CalledProcessError) as e:
            raise SystemExit(f"tmlint --changed: git failed: {e}")
        for line in proc.stdout.splitlines():
            p = root / line.strip()
            if p.suffix == ".py" and p.exists() and not engine._is_fixture(p):
                out.append(p)
    return sorted(set(out))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tmlint", description="repo-invariant static analyzer"
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to lint (default: tendermint_tpu/)",
    )
    ap.add_argument(
        "--rules",
        help="comma-separated rule codes to run (default: all)",
    )
    ap.add_argument(
        "--baseline",
        default=str(_REPO / engine.DEFAULT_BASELINE),
        help="findings baseline file (default: tools/tmlint_baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (report everything)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to the baseline file and exit 0",
    )
    ap.add_argument(
        "--changed",
        action="store_true",
        help="lint only files differing from HEAD (fast pre-commit mode)",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "-v",
        "--verbose",
        action="store_true",
        help="also show baselined and suppressed findings",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for code, rule in sorted(engine.all_rules().items()):
            print(f"{code}  {rule.description}")
        print("S001  suppression comment without a reason string")
        return 0

    if args.changed:
        paths = changed_files(_REPO)
        if not paths:
            print("tmlint: no changed python files")
            return 0
    elif args.paths:
        paths = [pathlib.Path(p) for p in args.paths]
        for p in paths:
            if not p.exists():
                print(f"tmlint: no such path: {p}", file=sys.stderr)
                return 2
    else:
        paths = [_REPO / "tendermint_tpu"]

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]

    baseline = None if args.no_baseline else args.baseline
    try:
        report = engine.lint_paths(
            paths, rules=rules, baseline_path=baseline, root=_REPO
        )
    except ValueError as e:
        print(f"tmlint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        engine.write_baseline(args.baseline, report.findings)
        print(
            f"tmlint: baselined {len(report.findings)} finding(s) "
            f"-> {args.baseline}"
        )
        return 0

    print(engine.render_report(report, verbose=args.verbose))
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
