"""Headline benchmark: ed25519 commit verification + Merkle throughput.

Prints ONE JSON line. Primary metric is the BASELINE.md north star:
ed25519 verifies/sec/chip on a 10k-validator commit batch (target 1M/s;
vs_baseline is the ratio against that target since the reference
publishes no numbers of its own — BASELINE.json `published: {}`).

Runs on whatever backend JAX auto-selects (the real chip under axon).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _bench_sigs(n_sigs: int):
    sys.stderr.write(f"preparing {n_sigs} signatures...\n")
    from tendermint_tpu.crypto.keys import gen_priv_key

    # one key per distinct validator is realistic but slow to generate;
    # cycle 256 keys over the batch (device cost is identical per lane).
    privs = [gen_priv_key(bytes([i]) * 32) for i in range(min(256, n_sigs))]
    msgs = [
        b'{"chain_id":"bench-chain","vote":{"height":9,"round":0,"type":2,"index":%d}}'
        % i
        for i in range(n_sigs)
    ]
    sigs = [privs[i % len(privs)].sign(m) for i, m in enumerate(msgs)]
    pubs = [privs[i % len(privs)].pub_key.data for i in range(n_sigs)]
    return pubs, msgs, sigs


def _bench_verify_tables(n_vals: int, stack: int = 64, warm_reps: int = 4) -> dict:
    """Steady-state consensus path: cached valset comb tables
    (ops.ed25519_tables, the TableBatchVerifier backend).

    Measures two shapes:
    * one commit (B = n_vals lanes) — the consensus-loop latency number
      (runs the materialized-entries pallas chain; K=1 doesn't tile the
      fused kernel);
    * `stack` commits of the same valset stacked into one device batch
      (B = stack*n_vals) — the fast-sync throughput number (BASELINE
      config 3 shape), which takes the FUSED select+accumulate pallas
      kernel (in-kernel table selection, table read once per launch).
      Stacking matters because launches neither pipeline nor come free
      (~60 ms fixed dispatch overhead measured through the axon
      tunnel), so per-execution work must be large.
    """
    import jax

    from tendermint_tpu.ops.ed25519_tables import (
        build_key_tables,
        prepare_commit_lanes,
        verify_tables_kernel,
    )

    pubs, msgs, sigs = _bench_sigs(n_vals)
    pub_arr = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(n_vals, 32)

    t0 = time.time()
    tables, key_ok = build_key_tables(pub_arr)
    tables.block_until_ready()
    build_s = time.time() - t0
    assert key_ok.all()

    t0 = time.time()
    s, h, r, pre = prepare_commit_lanes(pubs, [(msgs, sigs)])
    prep_s = time.time() - t0
    assert pre.all()

    def _warm_time(s_, h_, r_, reps):
        s_d, h_d, r_d = jax.device_put(s_), jax.device_put(h_), jax.device_put(r_)
        t0 = time.time()
        out = np.asarray(verify_tables_kernel(tables, s_d, h_d, r_d))
        compile_s = time.time() - t0
        assert out.all(), "tables path rejected valid signatures"
        best = float("inf")
        for _ in range(reps):
            t0 = time.time()
            np.asarray(verify_tables_kernel(tables, s_d, h_d, r_d))
            best = min(best, time.time() - t0)
        return best, compile_s

    one_s, compile_s = _warm_time(s, h, r, warm_reps)

    ks = np.tile(s, (stack, 1))
    kh = np.tile(h, (stack, 1))
    kr = np.tile(r, (stack, 1))
    stack_s, stack_compile_s = _warm_time(ks, kh, kr, warm_reps)

    # valset-diff rebuild: swap ONE validator and rebuild through the
    # service's incremental path (host-build the 1 new key + device
    # gather of the unchanged columns) — vs table_build_s from scratch
    from tendermint_tpu.crypto.keys import gen_priv_key as _gen
    from tendermint_tpu.services import TableBatchVerifier

    svc = TableBatchVerifier()
    svc._tables[svc._cache_key(tuple(pubs))] = (tuple(pubs), tables, key_ok)
    rebuild_s = None
    for seed in (b"\xaa", b"\xbb"):  # 2nd run = warm (gather jit cached)
        pubs2 = list(pubs)
        pubs2[n_vals // 2] = _gen(seed * 32).pub_key.data
        t0 = time.time()
        t2, ok2 = svc._tables_for(tuple(pubs2))
        np.asarray(t2[0, 0, 0, :4])  # d2h fetch = the axon sync point
        np.asarray(ok2)
        rebuild_s = time.time() - t0

    return {
        "rebuild_1key_s": round(rebuild_s, 2),
        "n": n_vals,
        "stack": stack,
        "table_build_s": round(build_s, 2),
        "host_prep_s": round(prep_s, 4),
        "compile_s": round(compile_s + stack_compile_s, 2),
        "warm_s": one_s,
        "commit_ms": round(one_s * 1e3, 2),
        "stacked_warm_s": stack_s,
        "verifies_per_s": stack * n_vals / stack_s,
    }


def _bench_verify(n_sigs: int, warm_reps: int = 3) -> dict:
    """Generic-ladder path (ad-hoc triples, no cached valset)."""
    from tendermint_tpu.ops.ed25519_kernel import bucket_size, prepare_batch, verify_kernel
    from tendermint_tpu.parallel.mesh import pad_to_multiple

    pubs, msgs, sigs = _bench_sigs(n_sigs)
    pub, r, s, h, pre = prepare_batch(pubs, msgs, sigs)
    size = bucket_size(n_sigs)
    (pub, r, s, h), _, _ = pad_to_multiple(
        [pub, r, s, h], np.zeros(n_sigs, dtype=np.int32), size
    )

    t0 = time.time()
    out = np.asarray(verify_kernel(pub, r, s, h))
    compile_s = time.time() - t0
    assert out[:n_sigs].all(), "bench batch failed to verify"

    best = float("inf")
    for _ in range(warm_reps):
        t0 = time.time()
        np.asarray(verify_kernel(pub, r, s, h))
        best = min(best, time.time() - t0)
    return {
        "n": n_sigs,
        "padded": size,
        "compile_s": round(compile_s, 2),
        "warm_s": best,
        # honest throughput: real signatures completed per second (the
        # padded lanes do run, but a real commit only needs n_sigs)
        "verifies_per_s": n_sigs / best,
    }


def _bench_merkle(n_leaves: int, leaf_bytes: int = 64, stack: int = 16) -> dict:
    """Single 65k-leaf root (latency) + a `stack`-tree forest in one
    device launch (throughput — BASELINE config 4's batched shape)."""
    from tendermint_tpu.merkle.simple import simple_hash_from_byte_slices
    from tendermint_tpu.ops.merkle_kernel import merkle_root_device, merkle_roots_forest

    items = [bytes([i % 256]) * leaf_bytes for i in range(n_leaves)]
    t0 = time.time()
    root = merkle_root_device(items)
    compile_s = time.time() - t0
    assert root == simple_hash_from_byte_slices(items), "device root != host root"
    t0 = time.time()
    merkle_root_device(items)
    warm = time.time() - t0

    forest = [items] * stack
    t0 = time.time()
    roots = merkle_roots_forest(forest)
    forest_compile_s = time.time() - t0
    assert all(r == root for r in roots)
    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        merkle_roots_forest(forest)
        best = min(best, time.time() - t0)
    return {
        "n_leaves": n_leaves,
        "compile_s": round(compile_s + forest_compile_s, 2),
        "warm_s": warm,
        "stack": stack,
        "forest_warm_s": best,
        "leaves_per_s": stack * n_leaves / best,
    }


def main() -> None:
    import jax

    sys.stderr.write(f"devices: {jax.devices()}\n")
    t10k = _bench_verify_tables(10_240, stack=64)
    sys.stderr.write(f"tables@10k: {t10k}\n")
    # fast-sync shape at 1k validators (BASELINE config 3): a window of
    # commits batched per device call -> blocks verified per second
    t1k = _bench_verify_tables(1_024, stack=64)
    sys.stderr.write(f"tables@1k x64: {t1k}\n")
    v1k = _bench_verify(1_000)
    sys.stderr.write(f"generic@1k: {v1k}\n")
    # ad-hoc batches large enough to clear the ~60 ms dispatch floor
    # (the service accumulates ad-hoc triples, so big flushes are the
    # realistic heavy-load shape; docs/PLATFORM_NOTES.md has the floor)
    v8k = _bench_verify(8_000)
    sys.stderr.write(f"generic@8k: {v8k}\n")
    m = _bench_merkle(65_536)
    sys.stderr.write(f"merkle@65k: {m}\n")

    target = 1_000_000.0  # BASELINE.md: >=1M ed25519 verifies/s/chip
    result = {
        "metric": "ed25519_verifies_per_sec_per_chip",
        "value": round(t10k["verifies_per_s"], 1),
        "unit": "verifies/s",
        "vs_baseline": round(t10k["verifies_per_s"] / target, 4),
        "detail": {
            "commit_10k_validators_ms": t10k["commit_ms"],
            "fastsync_stack": t10k["stack"],
            "fastsync_batch_ms": round(t10k["stacked_warm_s"] * 1e3, 2),
            "fastsync_blocks_per_s_1k_vals": round(
                t1k["stack"] / t1k["stacked_warm_s"], 1
            ),
            "commit_1k_validators_ms": t1k["commit_ms"],
            "table_build_10k_s": t10k["table_build_s"],
            "table_rebuild_1key_s": t10k["rebuild_1key_s"],
            "host_prep_10k_s": t10k["host_prep_s"],
            "generic_ladder_verifies_per_s": round(v1k["verifies_per_s"], 1),
            "generic_ladder_8k_verifies_per_s": round(v8k["verifies_per_s"], 1),
            "merkle_leaves_per_s": round(m["leaves_per_s"], 1),
            "merkle_65k_ms": round(m["warm_s"] * 1e3, 2),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
