"""Headline benchmark: ed25519 commit verification + Merkle throughput.

Prints ONE JSON line. Primary metric is the BASELINE.md north star:
ed25519 verifies/sec/chip on a 10k-validator commit batch (target 1M/s;
vs_baseline is the ratio against that target since the reference
publishes no numbers of its own — BASELINE.json `published: {}`).

Runs on whatever backend JAX auto-selects (the real chip under axon).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _bench_verify(n_sigs: int, warm_reps: int = 3) -> dict:
    from tendermint_tpu.ops.ed25519_kernel import bucket_size, prepare_batch, verify_kernel
    from tendermint_tpu.parallel.mesh import pad_to_multiple

    sys.stderr.write(f"preparing {n_sigs} signatures...\n")
    from tendermint_tpu.crypto.keys import gen_priv_key

    # one key per distinct validator is realistic but slow to generate;
    # cycle 256 keys over the batch (device cost is identical per lane).
    privs = [gen_priv_key(bytes([i]) * 32) for i in range(min(256, n_sigs))]
    msgs = [
        b'{"chain_id":"bench-chain","vote":{"height":9,"round":0,"type":2,"index":%d}}'
        % i
        for i in range(n_sigs)
    ]
    sigs = [privs[i % len(privs)].sign(m) for i, m in enumerate(msgs)]
    pubs = [privs[i % len(privs)].pub_key.data for i in range(n_sigs)]
    pub, r, s, h, pre = prepare_batch(pubs, msgs, sigs)
    size = bucket_size(n_sigs)
    (pub, r, s, h), _, _ = pad_to_multiple(
        [pub, r, s, h], np.zeros(n_sigs, dtype=np.int32), size
    )

    t0 = time.time()
    out = np.asarray(verify_kernel(pub, r, s, h))
    compile_s = time.time() - t0
    assert out[:n_sigs].all(), "bench batch failed to verify"

    best = float("inf")
    for _ in range(warm_reps):
        t0 = time.time()
        np.asarray(verify_kernel(pub, r, s, h))
        best = min(best, time.time() - t0)
    return {
        "n": n_sigs,
        "padded": size,
        "compile_s": round(compile_s, 2),
        "warm_s": best,
        # honest throughput: real signatures completed per second (the
        # padded lanes do run, but a real commit only needs n_sigs)
        "verifies_per_s": n_sigs / best,
    }


def _bench_merkle(n_leaves: int, leaf_bytes: int = 64) -> dict:
    from tendermint_tpu.ops.merkle_kernel import merkle_root_device

    items = [bytes([i % 256]) * leaf_bytes for i in range(n_leaves)]
    t0 = time.time()
    merkle_root_device(items)
    compile_s = time.time() - t0
    t0 = time.time()
    merkle_root_device(items)
    warm = time.time() - t0
    return {
        "n_leaves": n_leaves,
        "compile_s": round(compile_s, 2),
        "warm_s": warm,
        "leaves_per_s": n_leaves / warm,
    }


def main() -> None:
    import jax

    sys.stderr.write(f"devices: {jax.devices()}\n")
    v10k = _bench_verify(10_000)
    sys.stderr.write(f"verify@10k: {v10k}\n")
    v1k = _bench_verify(1_000)
    sys.stderr.write(f"verify@1k: {v1k}\n")
    m = _bench_merkle(65_536)
    sys.stderr.write(f"merkle@65k: {m}\n")

    target = 1_000_000.0  # BASELINE.md: >=1M ed25519 verifies/s/chip
    result = {
        "metric": "ed25519_verifies_per_sec_per_chip",
        "value": round(v10k["verifies_per_s"], 1),
        "unit": "verifies/s",
        "vs_baseline": round(v10k["verifies_per_s"] / target, 4),
        "detail": {
            "commit_10k_validators_ms": round(v10k["warm_s"] * 1e3, 2),
            "commit_1k_validators_ms": round(v1k["warm_s"] * 1e3, 2),
            "merkle_leaves_per_s": round(m["leaves_per_s"], 1),
            "merkle_65k_ms": round(m["warm_s"] * 1e3, 2),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
