"""Headline benchmark: ed25519 commit verification + Merkle throughput.

Prints ONE JSON line. Primary metric is the BASELINE.md north star:
ed25519 verifies/sec/chip on a 10k-validator commit batch (target 1M/s;
vs_baseline is the ratio against that target since the reference
publishes no numbers of its own — BASELINE.json `published: {}`).

Runs on whatever backend JAX auto-selects (the real chip under axon).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

# compile once per machine, not per process: the persistent executable
# cache turns the multi-minute XLA compiles into millisecond loads
# (utils/jax_cache.py; the r4 79s "table build" was ~95% compile)
from tendermint_tpu.utils.jax_cache import enable_persistent_cache

enable_persistent_cache()


def _best_of(fn, reps: int) -> float:
    """Min wall time over reps — robust to background machine load (the
    r3->r4 merkle 'regression' was a single noisy sample)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        fn()
        best = min(best, time.time() - t0)
    return best


def _bench_sigs(n_sigs: int):
    sys.stderr.write(f"preparing {n_sigs} signatures...\n")
    from tendermint_tpu.crypto.keys import gen_priv_key

    # one key per distinct validator is realistic but slow to generate;
    # cycle 256 keys over the batch (device cost is identical per lane).
    privs = [gen_priv_key(bytes([i]) * 32) for i in range(min(256, n_sigs))]
    msgs = [
        b'{"chain_id":"bench-chain","vote":{"height":9,"round":0,"type":2,"index":%d}}'
        % i
        for i in range(n_sigs)
    ]
    sigs = [privs[i % len(privs)].sign(m) for i, m in enumerate(msgs)]
    pubs = [privs[i % len(privs)].pub_key.data for i in range(n_sigs)]
    return pubs, msgs, sigs


def _bench_verify_tables(n_vals: int, stack: int = 64, warm_reps: int = 4) -> dict:
    """Steady-state consensus path: cached valset comb tables
    (ops.ed25519_tables, the TableBatchVerifier backend).

    Measures two shapes:
    * one commit (B = n_vals lanes) — the consensus-loop latency number
      (runs the materialized-entries pallas chain; K=1 doesn't tile the
      fused kernel);
    * `stack` commits of the same valset stacked into one device batch
      (B = stack*n_vals) — the fast-sync throughput number (BASELINE
      config 3 shape), which takes the FUSED select+accumulate pallas
      kernel (in-kernel table selection, table read once per launch).
      Stacking matters because launches neither pipeline nor come free
      (~60 ms fixed dispatch overhead measured through the axon
      tunnel), so per-execution work must be large.
    """
    import jax

    from tendermint_tpu.ops.ed25519_tables import (
        build_key_tables,
        prepare_commit_lanes,
        verify_tables_kernel,
    )

    pubs, msgs, sigs = _bench_sigs(n_vals)
    pub_arr = np.frombuffer(b"".join(pubs), dtype=np.uint8).reshape(n_vals, 32)

    # first build: includes the one-time per-process executable
    # deserialize + device program upload (~25 s through the axon
    # tunnel even on a compile-cache hit — docs/PLATFORM_NOTES.md)
    t0 = time.time()
    tables, key_ok = build_key_tables(pub_arr)
    np.asarray(tables[0, 0, 0, :4])  # real sync (block_until_ready no-ops under axon)
    build_first_s = time.time() - t0
    assert key_ok.all()
    # steady-state build: what every later valset rotation pays
    t0 = time.time()
    tables, key_ok = build_key_tables(pub_arr)
    np.asarray(tables[0, 0, 0, :4])
    build_s = time.time() - t0

    t0 = time.time()
    s, h, r, pre = prepare_commit_lanes(pubs, [(msgs, sigs)])
    prep_s = time.time() - t0
    assert pre.all()

    def _warm_time(s_, h_, r_, reps):
        s_d, h_d, r_d = jax.device_put(s_), jax.device_put(h_), jax.device_put(r_)
        t0 = time.time()
        out = np.asarray(verify_tables_kernel(tables, s_d, h_d, r_d))
        compile_s = time.time() - t0
        assert out.all(), "tables path rejected valid signatures"
        best = float("inf")
        for _ in range(reps):
            t0 = time.time()
            np.asarray(verify_tables_kernel(tables, s_d, h_d, r_d))
            best = min(best, time.time() - t0)
        return best, compile_s

    one_s, compile_s = _warm_time(s, h, r, warm_reps)

    ks = np.tile(s, (stack, 1))
    kh = np.tile(h, (stack, 1))
    kr = np.tile(r, (stack, 1))
    stack_s, stack_compile_s = _warm_time(ks, kh, kr, warm_reps)

    # valset-diff rebuild: swap ONE validator and rebuild through the
    # service's incremental path (host-build the 1 new key + device
    # gather of the unchanged columns) — vs table_build_s from scratch
    from tendermint_tpu.crypto.keys import gen_priv_key as _gen
    from tendermint_tpu.services import TableBatchVerifier

    svc = TableBatchVerifier()
    svc._tables[svc._cache_key(tuple(pubs))] = (tuple(pubs), tables, key_ok)
    rebuild_s = None
    for seed in (b"\xaa", b"\xbb"):  # 2nd run = warm (gather jit cached)
        pubs2 = list(pubs)
        pubs2[n_vals // 2] = _gen(seed * 32).pub_key.data
        t0 = time.time()
        t2, ok2 = svc._tables_for(tuple(pubs2))
        np.asarray(t2[0, 0, 0, :4])  # d2h fetch = the axon sync point
        np.asarray(ok2)
        rebuild_s = time.time() - t0

    # 500-key valset rotation: half-thousand NEW keys arrive at once —
    # the incremental path must device-build just the missing block and
    # gather the survivors (VERDICT r4 item 4)
    turnover_s = None
    if n_vals >= 1000:
        pubs3 = list(pubs)
        for i in range(500):
            pubs3[i * 2] = _gen((b"T%03d" % i).ljust(32, b"\x00")).pub_key.data
        t0 = time.time()
        t3, ok3 = svc._tables_for(tuple(pubs3))
        np.asarray(t3[0, 0, 0, :4])
        np.asarray(ok3)
        turnover_s = time.time() - t0

    return {
        "rebuild_1key_s": round(rebuild_s, 2),
        "turnover_500_s": round(turnover_s, 2) if turnover_s else None,
        "n": n_vals,
        "stack": stack,
        "table_build_s": round(build_s, 2),
        "table_build_first_s": round(build_first_s, 2),
        "host_prep_s": round(prep_s, 4),
        "compile_s": round(compile_s + stack_compile_s, 2),
        "warm_s": one_s,
        "commit_ms": round(one_s * 1e3, 2),
        "stacked_warm_s": stack_s,
        # marginal cost of one more commit inside a K=stack launch — the
        # number the BASELINE <2 ms commit target maps to on a device
        # with a ~60 ms fixed launch floor (docs/PLATFORM_NOTES.md)
        "commit_marginal_ms": round(stack_s * 1e3 / stack, 2),
        "verifies_per_s": stack * n_vals / stack_s,
    }


def _bench_verify(n_sigs: int, warm_reps: int = 4) -> dict:
    """Generic-ladder path (ad-hoc triples, no cached valset): the
    pallas VMEM-resident ladder for >= 1024-lane buckets on TPU
    (`ops.ed25519_ladder_pallas`), the XLA scan below."""
    import jax

    from tendermint_tpu.ops.ed25519_kernel import bucket_size, prepare_batch, verify_kernel
    from tendermint_tpu.parallel.mesh import pad_to_multiple

    pubs, msgs, sigs = _bench_sigs(n_sigs)
    pub, r, s, h, pre = prepare_batch(pubs, msgs, sigs)
    size = bucket_size(n_sigs)
    (pub, r, s, h), _, _ = pad_to_multiple(
        [pub, r, s, h], np.zeros(n_sigs, dtype=np.int32), size
    )
    from tendermint_tpu.ops.ed25519_ladder_pallas import (
        use_pallas_ladder,
        verify_kernel_pallas,
    )

    kernel = verify_kernel_pallas if use_pallas_ladder(size) else verify_kernel

    t0 = time.time()
    out = np.asarray(kernel(pub, r, s, h))
    compile_s = time.time() - t0
    assert out[:n_sigs].all(), "bench batch failed to verify"

    best = _best_of(lambda: np.asarray(kernel(pub, r, s, h)), warm_reps)
    return {
        "n": n_sigs,
        "padded": size,
        "compile_s": round(compile_s, 2),
        "warm_s": best,
        # honest throughput: real signatures completed per second (the
        # padded lanes do run, but a real commit only needs n_sigs)
        "verifies_per_s": n_sigs / best,
    }


def _bench_merkle(n_leaves: int, leaf_bytes: int = 64, stack: int = 16) -> dict:
    """Single 65k-leaf root (latency) + a `stack`-tree forest in one
    device launch (throughput — BASELINE config 4's batched shape)."""
    from tendermint_tpu.merkle.simple import simple_hash_from_byte_slices
    from tendermint_tpu.ops.merkle_kernel import merkle_root_device, merkle_roots_forest

    items = [bytes([i % 256]) * leaf_bytes for i in range(n_leaves)]
    t0 = time.time()
    root = merkle_root_device(items)
    compile_s = time.time() - t0
    assert root == simple_hash_from_byte_slices(items), "device root != host root"
    warm = _best_of(lambda: merkle_root_device(items), 5)

    forest = [items] * stack
    t0 = time.time()
    roots = merkle_roots_forest(forest)
    forest_compile_s = time.time() - t0
    assert all(r == root for r in roots)
    best = _best_of(lambda: merkle_roots_forest(forest), 5)
    return {
        "n_leaves": n_leaves,
        "compile_s": round(compile_s + forest_compile_s, 2),
        "warm_s": warm,
        "stack": stack,
        "forest_warm_s": best,
        "leaves_per_s": stack * n_leaves / best,
    }


def _bench_block_build(n_txs: int = 65_536) -> dict:
    """End-to-end production seam: a 65k-tx Block built through the node's
    device TreeHasher (`Block.make_block` -> `Txs.hash` ->
    `merkle_root_device`), bit-identical to host (BASELINE config 4 as a
    production path, reference `types/tx.go:33-46`)."""
    from tendermint_tpu.merkle.simple import simple_hash_from_byte_slices
    from tendermint_tpu.services.hasher import TreeHasher
    from tendermint_tpu.types import BlockID, Txs
    from tendermint_tpu.types.block import Block, Commit

    txs = Txs(b"bench-tx-%06d" % i for i in range(n_txs))
    dev = TreeHasher(backend="device")

    def build():
        return Block.make_block(
            height=1,
            chain_id="bench-chain",
            txs=txs,
            last_commit=Commit.empty(),
            last_block_id=BlockID.zero(),
            time=1,
            validators_hash=b"\x01" * 20,
            app_hash=b"",
            hasher=dev,
        )

    t0 = time.time()
    blk = build()
    first_s = time.time() - t0
    assert blk.header.data_hash == simple_hash_from_byte_slices(list(txs))
    best = _best_of(build, 3)
    return {
        "n_txs": n_txs,
        "first_s": round(first_s, 2),
        "block_build_s": best,
        "txs_per_s": n_txs / best,
    }


def main() -> None:
    import jax

    sys.stderr.write(f"devices: {jax.devices()}\n")
    t10k = _bench_verify_tables(10_240, stack=64)
    sys.stderr.write(f"tables@10k: {t10k}\n")
    # fast-sync shape at 1k validators (BASELINE config 3): a window of
    # commits batched per device call -> blocks verified per second
    t1k = _bench_verify_tables(1_024, stack=64)
    sys.stderr.write(f"tables@1k x64: {t1k}\n")
    v1k = _bench_verify(1_000)
    sys.stderr.write(f"generic@1k: {v1k}\n")
    # ad-hoc batches large enough to clear the ~60 ms dispatch floor
    # (the service accumulates ad-hoc triples, so big flushes are the
    # realistic heavy-load shape; docs/PLATFORM_NOTES.md has the floor)
    v8k = _bench_verify(8_000)
    sys.stderr.write(f"generic@8k: {v8k}\n")
    # the big-flush shape: what a light client or cold fast-sync with no
    # cached tables can push through one pallas-ladder launch
    v64k = _bench_verify(65_536)
    sys.stderr.write(f"generic@64k: {v64k}\n")
    m = _bench_merkle(65_536)
    sys.stderr.write(f"merkle@65k: {m}\n")
    bb = _bench_block_build(65_536)
    sys.stderr.write(f"block_build@65k: {bb}\n")

    target = 1_000_000.0  # BASELINE.md: >=1M ed25519 verifies/s/chip
    result = {
        "metric": "ed25519_verifies_per_sec_per_chip",
        "value": round(t10k["verifies_per_s"], 1),
        "unit": "verifies/s",
        "vs_baseline": round(t10k["verifies_per_s"] / target, 4),
        "detail": {
            "commit_10k_validators_ms": t10k["commit_ms"],
            "fastsync_stack": t10k["stack"],
            "fastsync_batch_ms": round(t10k["stacked_warm_s"] * 1e3, 2),
            "fastsync_blocks_per_s_1k_vals": round(
                t1k["stack"] / t1k["stacked_warm_s"], 1
            ),
            "commit_1k_validators_ms": t1k["commit_ms"],
            "commit_marginal_ms_at_k64": t10k["commit_marginal_ms"],
            "table_build_10k_s": t10k["table_build_s"],
            "table_build_first_10k_s": t10k["table_build_first_s"],
            "table_rebuild_1key_s": t10k["rebuild_1key_s"],
            "table_turnover_500key_s": t10k["turnover_500_s"],
            "host_prep_10k_s": t10k["host_prep_s"],
            "generic_ladder_verifies_per_s": round(v1k["verifies_per_s"], 1),
            "generic_ladder_8k_verifies_per_s": round(v8k["verifies_per_s"], 1),
            "generic_ladder_64k_verifies_per_s": round(v64k["verifies_per_s"], 1),
            "merkle_leaves_per_s": round(m["leaves_per_s"], 1),
            "merkle_65k_ms": round(m["warm_s"] * 1e3, 2),
            "block_build_65k_tx_s": round(bb["block_build_s"], 3),
        },
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
